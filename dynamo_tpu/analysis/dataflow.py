"""Flow-sensitive dataflow for dtpu-lint v3: who is traced, who is
per-request, who is a compile-time constant.

The v2 call graph answers *reachability* questions (is this function on
the hot path? does it transitively block?). The compile/purity hazards
that gate the ROADMAP speed rounds are *value* questions: does a
per-request Python value reach a jit cache key? is this ``if`` branching
on a traced array? Those need an abstract interpretation, not a walk.

**Lattice** (one abstract value per expression)::

            TOP           (conflicting: traced on one path, per-request
             |             on another — rules treat it as "don't know")
      REQ         TRACED  (REQ: unbounded per-request Python data;
       |            |      TRACED: a jax array / tracer)
     SCALAR  ------+      (host Python scalar with a *bounded* image —
       |                   bools, comparisons, bucketed values)
     SHAPE                (derived from `.shape`/`len` of arrays: static
       |                   at trace time, a legitimate compile key)
     CONST                (literals, config attrs — one value per process)
       |
      BOT                 (unknown / not yet computed)

``REQ ⊔ TRACED = TOP`` instead of collapsing either way: merging "this
is per-request host data" with "this is device data" loses exactly the
distinction the rules exist to check, so the merge is marked
conflicting and the rules stay quiet on it (precision over recall).

**Abstract values** carry the lattice base plus the set of *parameter
indices* the value depends on — that pair is what makes function
summaries compose: ``def f(a, b): return (a, b)`` summarizes as
``ret = BOT{0,1}``, so a caller passing a REQ argument in position 0
sees REQ flow through the call without re-analyzing ``f``. REQ values
also carry a short ``src`` provenance chain (``request.seed → seed``)
so findings can render the taint path.

**Taint sources and sinks** (repo-tuned, documented in docs/ANALYSIS.md):

- parameters named ``request``/``req`` and any attribute chain rooted at
  them are REQ — one distinct value per request;
- ``self.config.*`` / ``self.spec.*`` / ``self.cfg.*`` are CONST — read
  once per process, safe in compile keys;
- ``jnp.*``/``jax.*``/``lax.*`` calls (and methods on traced values)
  produce TRACED — lifting REQ into a traced argument is the sanctioned
  "pass it as data" fix, so the call *kills* REQ taint;
- comparisons, ``bool()``, ``is``/``is not`` produce SCALAR: their image
  is finite, so branching/keying on them compiles a bounded program
  family (the bucketing idiom);
- ``.shape``/``.ndim``/``len()`` of traced values produce SHAPE: static
  at trace time, the legitimate shape-bucket compile key.

**Function summaries** (``Summary``): the return value's base + param
dependence, plus ``jit_key_params`` — which parameters flow into the
``key=`` of an ``instrumented_jit`` call inside the body. Summaries are
computed in two passes over the whole graph (pass 2 sees every summary
pass 1 produced) — enough for the repo's builder→helper call shapes
without a full interprocedural fixpoint.

Built once per :func:`run_analysis` via :func:`ensure_dataflow` and
shared by every dataflow rule through ``graph.dataflow`` — same
one-parse/one-graph discipline as the call graph itself.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.core import qualified_name

__all__ = [
    "BOT", "CONST", "SHAPE", "SCALAR", "REQ", "TRACED", "TOP",
    "AV", "BOT_AV", "FuncFacts", "Summary", "ProjectDataflow",
    "base_name", "ensure_dataflow", "join_base",
]

BOT, CONST, SHAPE, SCALAR, REQ, TRACED, TOP = range(7)

_BASE_NAMES = {BOT: "bot", CONST: "const", SHAPE: "shape",
               SCALAR: "py-scalar", REQ: "per-request", TRACED: "traced",
               TOP: "top"}

# Total order for the host chain BOT < CONST < SHAPE < SCALAR < REQ;
# TRACED sits beside it, TOP above everything.
_HOST_ORDER = {BOT: 0, CONST: 1, SHAPE: 2, SCALAR: 3, REQ: 4}

_REQ_PARAMS = {"request", "req"}
_CONST_SELF_PREFIXES = ("self.config", "self.spec", "self.cfg")
_TRACED_ROOTS = {"jnp", "jax", "lax"}
_SHAPE_ATTRS = {"shape", "ndim", "size"}

_MAX_SRC = 4  # provenance chain cap — findings stay readable


def base_name(base: int) -> str:
    return _BASE_NAMES.get(base, "?")


def join_base(a: int, b: int) -> int:
    if a == b:
        return a
    if a == BOT:
        return b
    if b == BOT:
        return a
    if TOP in (a, b):
        return TOP
    if TRACED in (a, b):
        other = b if a == TRACED else a
        return TOP if other == REQ else TRACED
    return a if _HOST_ORDER[a] >= _HOST_ORDER[b] else b


class AV:
    """One abstract value: lattice base + parameter dependence + (for
    REQ) the provenance chain that findings render."""

    __slots__ = ("base", "params", "src")

    def __init__(self, base: int = BOT, params: frozenset = frozenset(),
                 src: tuple = ()):
        self.base = base
        self.params = params
        self.src = src[:_MAX_SRC]

    def join(self, other: "AV") -> "AV":
        base = join_base(self.base, other.base)
        params = self.params | other.params
        # keep the provenance of whichever side carries the taint
        if self.base == REQ and self.src:
            src = self.src
        elif other.base == REQ and other.src:
            src = other.src
        else:
            src = self.src or other.src
        return AV(base, params, src)

    def with_src(self, label: str) -> "AV":
        if self.src and self.src[-1] == label:
            return self
        return AV(self.base, self.params, (*self.src, label))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        dep = f"{{{','.join(map(str, sorted(self.params)))}}}" \
            if self.params else ""
        return f"AV({base_name(self.base)}{dep})"


BOT_AV = AV()


def join_env(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        prev = out.get(k)
        out[k] = v if prev is None else prev.join(v)
    return out


class Summary:
    """What a caller needs to know without re-analyzing the body."""

    __slots__ = ("ret", "param_names", "jit_key_params")

    def __init__(self, ret: AV, param_names: list,
                 jit_key_params: dict):
        self.ret = ret
        self.param_names = param_names
        # param index -> (param name, line of the instrumented_jit site
        # whose key= the param reaches)
        self.jit_key_params = jit_key_params


class FuncFacts:
    """Per-function analysis result: every evaluated expression's AV
    (by node identity), the points rules care about, and the summary."""

    __slots__ = ("fn", "env", "values", "returns", "key_sites", "tests",
                 "joined", "summary", "traced_count")

    def __init__(self, fn):
        self.fn = fn
        self.env: dict = {}
        self.values: dict = {}          # id(node) -> AV
        self.returns: AV = BOT_AV
        self.key_sites: list = []       # (call node, key expr node, AV)
        self.tests: list = []           # (node, AV, kind) boolean contexts
        self.joined: list = []          # (JoinedStr/% node, AV) formats
        self.summary: Summary | None = None
        self.traced_count = 0           # nodes that evaluated TRACED

    def value(self, node: ast.AST) -> AV:
        return self.values.get(id(node), BOT_AV)


class _Evaluator:
    """Flow-sensitive walk of one function body.

    Loops run twice (join with the pre-loop env after) so loop-carried
    rebinding reaches a post-fixpoint for this lattice's tiny height;
    branches analyze both arms and join.
    """

    def __init__(self, df: "ProjectDataflow", fn, facts: FuncFacts,
                 params_av: dict, closure_env: dict | None = None,
                 trace_nested: bool = False):
        self.df = df
        self.fn = fn
        self.facts = facts
        self.trace_nested = trace_nested
        self.sites = {id(s.node): s for s in fn.calls}
        env: dict = dict(closure_env or {})
        env.update(params_av)
        self.env = env

    # -- statements -----------------------------------------------------------

    def run(self, body: list) -> dict:
        self.exec_block(body, self.env)
        self.facts.env = self.env
        return self.env

    def exec_block(self, stmts: list, env: dict) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, stmt: ast.stmt, env: dict) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            av = self.eval(value, env) if value is not None else BOT_AV
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            if isinstance(stmt, ast.AugAssign):
                old = env.get(getattr(stmt.target, "id", ""), BOT_AV)
                av = old.join(av)
            for t in targets:
                self.bind(t, av, env)
        elif isinstance(stmt, ast.Return):
            av = self.eval(stmt.value, env) if stmt.value is not None \
                else AV(CONST)
            self.facts.returns = self.facts.returns.join(av)
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value, env)
        elif isinstance(stmt, ast.If):
            tv = self.eval(stmt.test, env)
            self.facts.tests.append((stmt.test, tv, "if"))
            then_env = dict(env)
            self.exec_block(stmt.body, then_env)
            else_env = dict(env)
            self.exec_block(stmt.orelse, else_env)
            env.clear()
            env.update(join_env(then_env, else_env))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            iv = self.eval(stmt.iter, env)
            elem = self.element_of(iv, stmt.iter)
            pre = dict(env)
            for _ in range(2):
                self.bind(stmt.target, elem, env)
                self.exec_block(stmt.body, env)
            self.exec_block(stmt.orelse, env)
            merged = join_env(pre, env)
            env.clear()
            env.update(merged)
        elif isinstance(stmt, ast.While):
            pre = dict(env)
            for _ in range(2):
                tv = self.eval(stmt.test, env)
                if _ == 0:
                    self.facts.tests.append((stmt.test, tv, "while"))
                self.exec_block(stmt.body, env)
            self.exec_block(stmt.orelse, env)
            merged = join_env(pre, env)
            env.clear()
            env.update(merged)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                cv = self.eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, cv, env)
            self.exec_block(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            self.exec_block(stmt.body, env)
            for handler in stmt.handlers:
                if handler.name:
                    env[handler.name] = BOT_AV
                self.exec_block(handler.body, env)
            self.exec_block(stmt.orelse, env)
            self.exec_block(stmt.finalbody, env)
        elif isinstance(stmt, ast.Assert):
            tv = self.eval(stmt.test, env)
            self.facts.tests.append((stmt.test, tv, "assert"))
            if stmt.msg is not None:
                self.eval(stmt.msg, env)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[stmt.name] = AV(CONST)
            if self.trace_nested:
                # program-body mode: nested defs (scan `step` closures)
                # are traced inline with traced params and this env as
                # their closure.
                nested = self.fn.nested.get(stmt.name) \
                    if hasattr(self.fn, "nested") else None
                if nested is not None:
                    self.df._analyze_into(
                        nested, self.facts, closure_env=dict(env),
                        params_base=TRACED, trace_nested=True)
        elif isinstance(stmt, ast.ClassDef):
            env[stmt.name] = AV(CONST)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    env.pop(t.id, None)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self.eval(stmt.exc, env)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                env[(alias.asname or alias.name).split(".")[0]] = AV(CONST)
        elif isinstance(stmt, ast.Match):
            self.eval(stmt.subject, env)
            pre = dict(env)
            merged: dict | None = None
            for case in stmt.cases:
                case_env = dict(pre)
                self.exec_block(case.body, case_env)
                merged = case_env if merged is None \
                    else join_env(merged, case_env)
            if merged is not None:
                env.clear()
                env.update(join_env(pre, merged))
        # Pass/Break/Continue/Global/Nonlocal: no dataflow effect.

    def bind(self, target: ast.AST, av: AV, env: dict) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = av
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.bind(el, av, env)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, av, env)
        # Attribute/Subscript stores: not tracked (self state is out of
        # scope for an intraprocedural pass).

    # -- expressions ----------------------------------------------------------

    def eval(self, node: ast.expr, env: dict) -> AV:
        av = self._eval(node, env)
        self.facts.values[id(node)] = av
        if av.base == TRACED:
            self.facts.traced_count += 1
        return av

    def _eval(self, node: ast.expr, env: dict) -> AV:
        if isinstance(node, ast.Constant):
            return AV(CONST)
        if isinstance(node, ast.Name):
            return env.get(node.id, BOT_AV)
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node, env)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            av = AV(CONST) if node.elts else AV(CONST)
            for el in node.elts:
                av = av.join(self.eval(el, env))
            return av
        if isinstance(node, ast.Dict):
            av = AV(CONST)
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    av = av.join(self.eval(k, env))
                av = av.join(self.eval(v, env))
            return av
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, ast.BinOp):
            left = self.eval(node.left, env)
            right = self.eval(node.right, env)
            if TRACED in (left.base, right.base):
                return AV(TRACED, left.params | right.params)
            return left.join(right)
        if isinstance(node, ast.UnaryOp):
            ov = self.eval(node.operand, env)
            if isinstance(node.op, ast.Not):
                self.facts.tests.append((node.operand, ov, "not"))
                if ov.base == TRACED:
                    return AV(TRACED, ov.params)
                return AV(SCALAR, ov.params)
            return ov
        if isinstance(node, ast.Compare):
            av = self.eval(node.left, env)
            for comp in node.comparators:
                av = av.join(self.eval(comp, env))
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                # identity tests (`x is None`) are Python-level and
                # static at trace time even on traced operands — jit
                # keys on pytree structure, so this is the sanctioned
                # optional-argument idiom
                return AV(SCALAR, av.params)
            if av.base == TRACED:
                # jnp comparisons yield arrays, not Python bools
                return AV(TRACED, av.params)
            # host comparisons have a bounded image: bucketing kills REQ
            return AV(SCALAR, av.params)
        if isinstance(node, ast.BoolOp):
            av = BOT_AV
            for v in node.values:
                vv = self.eval(v, env)
                self.facts.tests.append((v, vv, "boolop"))
                av = av.join(vv)
            return av
        if isinstance(node, ast.IfExp):
            tv = self.eval(node.test, env)
            self.facts.tests.append((node.test, tv, "ifexp"))
            return self.eval(node.body, env).join(
                self.eval(node.orelse, env))
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value, env)
            idx = self.eval(node.slice, env)
            return AV(base.base, base.params | idx.params, base.src)
        if isinstance(node, ast.Slice):
            av = BOT_AV
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    av = av.join(self.eval(part, env))
            return av
        if isinstance(node, ast.JoinedStr):
            av = AV(CONST)
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    av = av.join(self.eval(v.value, env))
            if av.base == REQ:
                self.facts.joined.append((node, av))
            return av
        if isinstance(node, ast.NamedExpr):
            av = self.eval(node.value, env)
            self.bind(node.target, av, env)
            return av
        if isinstance(node, ast.Lambda):
            return AV(CONST)
        if isinstance(node, ast.Await):
            return self.eval(node.value, env)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._eval_comp(node, env)
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            if node.value is not None:
                self.eval(node.value, env)
            return BOT_AV
        if isinstance(node, ast.FormattedValue):
            return self.eval(node.value, env)
        return BOT_AV

    def _eval_attribute(self, node: ast.Attribute, env: dict) -> AV:
        qn = qualified_name(node)
        if qn:
            root = qn.split(".", 1)[0]
            if root in _REQ_PARAMS and env.get(root, BOT_AV).base == REQ:
                return AV(REQ, env[root].params, (qn,))
            if qn.startswith(_CONST_SELF_PREFIXES):
                return AV(CONST)
        base = self.eval(node.value, env)
        if node.attr in _SHAPE_ATTRS and base.base in (TRACED, BOT,
                                                       CONST, SHAPE):
            return AV(SHAPE, base.params)
        if base.base == REQ:
            return base.with_src(f".{node.attr}")
        if base.base == TRACED:
            return AV(TRACED, base.params)
        return AV(base.base if base.base != SCALAR else BOT,
                  base.params, base.src)

    def _eval_comp(self, node, env: dict) -> AV:
        child = dict(env)
        for gen in node.generators:
            iv = self.eval(gen.iter, child)
            self.bind(gen.target, self.element_of(iv, gen.iter), child)
            for cond in gen.ifs:
                self.eval(cond, child)
        if isinstance(node, ast.DictComp):
            return self.eval(node.key, child).join(
                self.eval(node.value, child))
        return self.eval(node.elt, child)

    def element_of(self, av: AV, iter_node: ast.expr) -> AV:
        """Abstract value of one element when iterating ``av``."""
        if isinstance(iter_node, ast.Call):
            raw = qualified_name(iter_node.func)
            if raw in ("range", "enumerate", "zip", "sorted", "reversed"):
                out = BOT_AV
                for a in iter_node.args:
                    out = out.join(self.facts.value(a))
                return out
        if av.base == REQ:
            return av.with_src("[…]")
        return AV(av.base if av.base in (REQ, TRACED) else BOT,
                  av.params, av.src)

    def _eval_call(self, node: ast.Call, env: dict) -> AV:
        args = [self.eval(a, env) for a in node.args]
        kwargs = {kw.arg: self.eval(kw.value, env) for kw in node.keywords}
        raw = qualified_name(node.func)
        site = self.sites.get(id(node))

        # instrumented_jit: record what reaches the cache key
        if raw.endswith("instrumented_jit"):
            for kw in node.keywords:
                if kw.arg == "key":
                    self.facts.key_sites.append(
                        (node, kw.value, kwargs.get("key", BOT_AV)))

        # project callee with a summary: apply it
        if site is not None and site.callee is not None:
            summ = self.df.summaries.get(site.callee.qname)
            if summ is not None:
                return self._apply_summary(summ, args, kwargs)

        root = raw.split(".", 1)[0] if raw else ""
        if root in _TRACED_ROOTS:
            params = frozenset().union(
                *(a.params for a in args),
                *(a.params for a in kwargs.values())) \
                if (args or kwargs) else frozenset()
            return AV(TRACED, params)

        joined = BOT_AV
        for a in (*args, *kwargs.values()):
            joined = joined.join(a)

        if raw == "len":
            a0 = args[0] if args else BOT_AV
            if a0.base == REQ:
                return AV(REQ, a0.params, (*a0.src, "len(…)"))
            return AV(SHAPE, a0.params)
        if raw in ("bool", "isinstance", "hasattr", "callable", "issubclass"):
            return AV(SCALAR, joined.params)
        if raw in ("int", "float", "str", "repr", "hash"):
            if joined.base == REQ:
                return joined.with_src(f"{raw}(…)")
            if joined.base == TRACED:
                return AV(SCALAR, joined.params)
            return AV(joined.base, joined.params, joined.src)
        if raw in ("min", "max", "abs", "round", "sum", "sorted", "tuple",
                   "list", "set", "frozenset", "dict", "next", "getattr",
                   "range", "enumerate", "zip", "reversed", "divmod"):
            return joined

        # method call on a tainted / traced receiver
        if isinstance(node.func, ast.Attribute):
            recv = self.facts.value(node.func.value) \
                if id(node.func.value) in self.facts.values \
                else self.eval(node.func.value, env)
            if recv.base == TRACED:
                return AV(TRACED, recv.params | joined.params)
            if recv.base == REQ:
                return recv.with_src(f".{node.func.attr}(…)")
            if recv.base == CONST and raw.endswith(".format") \
                    and joined.base == REQ:
                self.facts.joined.append((node, joined))
                return joined
        return BOT_AV

    def _apply_summary(self, summ: Summary, args: list,
                       kwargs: dict) -> AV:
        base = summ.ret.base
        params: frozenset = frozenset()
        src: tuple = summ.ret.src
        for i in summ.ret.params:
            av = None
            if i < len(args):
                av = args[i]
            elif i < len(summ.param_names):
                av = kwargs.get(summ.param_names[i])
            if av is not None:
                base = join_base(base, av.base)
                params = params | av.params
                if av.base == REQ and av.src:
                    src = av.src
        return AV(base, params, src)


class ProjectDataflow:
    """Facts + summaries for every function in the graph, plus on-demand
    traced-body analyses for jitted program functions."""

    def __init__(self, graph):
        self.graph = graph
        self.summaries: dict = {}
        self.facts: dict = {}
        self._body_cache: dict = {}
        order = list(graph.functions.values())
        # two passes: pass 2 sees every summary pass 1 produced, which
        # covers the repo's builder -> helper -> jit call shapes
        for _ in range(2):
            for fn in order:
                self._analyze_function(fn)

    # -- generic per-function pass -------------------------------------------

    def _param_names(self, fn) -> list:
        a = fn.node.args
        names = [p.arg for p in (*a.posonlyargs, *a.args)]
        if names and names[0] in ("self", "cls") and fn.is_method:
            names = names[1:]
        return names

    def _analyze_function(self, fn) -> FuncFacts:
        facts = FuncFacts(fn)
        names = self._param_names(fn)
        params_av = {}
        for i, name in enumerate(names):
            if name in _REQ_PARAMS:
                params_av[name] = AV(REQ, frozenset({i}), (name,))
            else:
                params_av[name] = AV(BOT, frozenset({i}))
        a = fn.node.args
        for extra in (a.vararg, a.kwarg, *a.kwonlyargs):
            if extra is not None:
                pname = extra.arg
                if pname not in params_av:
                    params_av[pname] = AV(REQ, frozenset(), (pname,)) \
                        if pname in _REQ_PARAMS else BOT_AV
        if fn.is_method:
            params_av.setdefault("self", BOT_AV)
        ev = _Evaluator(self, fn, facts, params_av)
        ev.run(fn.node.body)
        key_params: dict = {}
        for _node, _expr, av in facts.key_sites:
            for p in av.params:
                if p < len(names):
                    key_params.setdefault(p, (names[p], _node.lineno))
        facts.summary = Summary(
            AV(facts.returns.base, facts.returns.params,
               facts.returns.src),
            names, key_params)
        self.facts[fn.qname] = facts
        self.summaries[fn.qname] = facts.summary
        return facts

    # -- traced program bodies ------------------------------------------------

    def _analyze_into(self, fn, facts: FuncFacts, closure_env: dict,
                      params_base: int, trace_nested: bool) -> None:
        """Analyze ``fn`` merging results into an existing ``facts``
        (used for nested scan-step closures traced inline)."""
        params_av = {}
        for i, name in enumerate(self._param_names(fn)):
            params_av[name] = AV(params_base, frozenset({i}))
        ev = _Evaluator(self, fn, facts, params_av,
                        closure_env=closure_env, trace_nested=trace_nested)
        ev.exec_block(fn.node.body, ev.env)

    def body_facts(self, body_fn, builder_fn) -> FuncFacts:
        """Facts for a jitted program body analyzed *as traced code*:
        parameters are TRACED, free variables resolve through the
        builder's final environment (its closure)."""
        cache_key = (body_fn.qname, builder_fn.qname)
        hit = self._body_cache.get(cache_key)
        if hit is not None:
            return hit
        builder_facts = self.facts.get(builder_fn.qname)
        closure = dict(builder_facts.env) if builder_facts is not None \
            else {}
        facts = FuncFacts(body_fn)
        params_av = {name: AV(TRACED, frozenset({i}))
                     for i, name in enumerate(self._param_names(body_fn))}
        ev = _Evaluator(self, body_fn, facts, params_av,
                        closure_env=closure, trace_nested=True)
        ev.run(body_fn.node.body)
        facts.summary = Summary(facts.returns, self._param_names(body_fn),
                                {})
        self._body_cache[cache_key] = facts
        return facts


def ensure_dataflow(graph) -> ProjectDataflow:
    """Build (once) and cache the project dataflow on the shared call
    graph — every dataflow rule in a run sees the same instance."""
    df = getattr(graph, "dataflow", None)
    if df is None:
        df = ProjectDataflow(graph)
        graph.dataflow = df
    return df
