"""engine-thread-shared-state: the poor-man's race detector for the
engine-thread / asyncio boundary.

The engine runs device work on a dedicated ``threading.Thread`` while
request handlers, the event plane, and status endpoints run on the
asyncio loop — two real OS threads sharing ``self``. An attribute
written from BOTH sides with no lock in scope is a data race: torn
read-modify-writes on counters, half-published dicts, state machines
skipping states. (CPython's GIL makes single stores atomic but nothing
composes — ``self.x += 1`` from two threads still loses updates.)

Scope is deliberately narrow to stay honest:

- only classes that actually *construct* a ``threading.Thread`` whose
  ``target=self.<method>``;
- engine side = the thread target(s) plus every same-class method
  transitively reachable from them via ``self.`` call edges;
- async side = the class's ``async def`` methods (nested async defs
  included) plus same-class methods reachable from them;
- writes in ``__init__``-family methods and in the thread-creating
  method itself are happens-before the thread start and exempt;
- a write inside a ``with <lock>``/``async with <lock>`` block counts
  as guarded (name-based lock-ness, same heuristic as
  lock-across-await).

A finding names the attribute and one write site from each side. Fix:
guard both sides with one lock, or funnel the write through a
single-owner side (e.g. the engine thread publishes, async only reads).

``lock-order-inversion`` (v3) extends the same lock heuristics with a
lockset analysis over the call graph: for every ``with <lock>:`` block,
the locks acquired inside it — directly nested, or transitively through
resolved project callees — define an acquisition order edge. Any pair
of locks witnessed in BOTH orders is a deadlock window between the
engine thread and the event loop (or any two threads), and the finding
renders both witness chains.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import CallGraphRule, Finding, iter_scope, \
    qualified_name

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}
_LOCKISH = ("lock", "mutex", "sem")


def _looks_like_lock(expr: ast.expr) -> bool:
    target = expr.func if isinstance(expr, ast.Call) else expr
    leaf = qualified_name(target).rsplit(".", 1)[-1].lower()
    return any(k in leaf for k in _LOCKISH)


def _under_lock(module, node: ast.AST, fn_node: ast.AST) -> bool:
    n = module.parent(node)
    while n is not None and n is not fn_node:
        if isinstance(n, (ast.With, ast.AsyncWith)) and any(
                _looks_like_lock(item.context_expr) for item in n.items):
            return True
        n = module.parent(n)
    return False


def _thread_targets(cls) -> list[str]:
    """Method names used as `threading.Thread(target=self.X)` in any
    method of the class (the creating method is recorded alongside)."""
    out = []
    for name, fn in cls.methods.items():
        for site in fn.calls:
            if site.raw.rsplit(".", 1)[-1] != "Thread":
                continue
            for kw in site.node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    out.append((kw.value.attr, name))
    return out


class EngineThreadSharedState(CallGraphRule):
    rule_id = "engine-thread-shared-state"
    description = ("attribute written both from engine-thread methods and "
                   "async event-loop methods of the same class with no "
                   "lock in scope: a cross-thread data race (torn "
                   "read-modify-writes, half-published state)")

    def check_graph(self, graph) -> Iterable[Finding]:
        for mi in graph.modules:
            for cls in mi.classes.values():
                yield from self._check_class(graph, mi, cls)

    def _check_class(self, graph, mi, cls) -> Iterable[Finding]:
        targets = _thread_targets(cls)
        if not targets:
            return
        creators = {creator for _, creator in targets}
        engine = self._closure(cls, [cls.methods[t] for t, _ in targets
                                     if t in cls.methods])
        async_roots = [fn for fn in self._class_functions(cls)
                       if fn.is_async]
        async_side = self._closure(cls, async_roots)
        exempt = _INIT_METHODS | creators
        # attr -> side -> first (fn, node, locked) write site
        writes: dict[str, dict[str, tuple]] = {}
        for fn in self._class_functions(cls):
            root = fn
            while root.parent is not None:
                root = root.parent
            if root.node.name in exempt:
                continue
            in_engine = fn.qname in engine or root.qname in engine
            in_async = fn.qname in async_side or root.qname in async_side
            if not (in_engine or in_async):
                continue
            for node, attr in self._self_writes(fn):
                locked = _under_lock(fn.module, node, fn.node)
                slot = writes.setdefault(attr, {})
                if in_engine:
                    slot.setdefault("engine", (fn, node, locked))
                if in_async:
                    slot.setdefault("async", (fn, node, locked))
        for attr in sorted(writes):
            slot = writes[attr]
            if "engine" not in slot or "async" not in slot:
                continue
            e_fn, e_node, e_locked = slot["engine"]
            a_fn, a_node, a_locked = slot["async"]
            if e_fn is a_fn and e_node is a_node:
                continue  # one site reachable from both sides: ambiguous
            if e_locked and a_locked:
                continue
            fn, node = (e_fn, e_node) if not e_locked else (a_fn, a_node)
            yield Finding(
                fn.module.path, node.lineno, node.col_offset, self.rule_id,
                f"`self.{attr}` is written from the engine thread "
                f"(`{e_fn.display}`) and the event loop "
                f"(`{a_fn.display}`) with no lock at this site",
                "guard both writers with one lock, or make a single side "
                "own the attribute (engine publishes, async reads), or "
                "suppress with the invariant that serializes the writes",
                chain=(f"{e_fn.display} [engine thread]",
                       f"{a_fn.display} [event loop]",
                       f"self.{attr}"))

    @staticmethod
    def _class_functions(cls):
        """Methods plus their nested defs (handlers defined inside
        methods run wherever they're awaited — usually the loop)."""
        out = []
        stack = list(cls.methods.values())
        while stack:
            fn = stack.pop()
            out.append(fn)
            stack.extend(fn.nested.values())
        return out

    @staticmethod
    def _closure(cls, roots) -> set[str]:
        """Qnames of same-class functions reachable from roots via
        resolved self-call edges (nested defs included)."""
        seen = {fn.qname for fn in roots}
        stack = list(roots)
        while stack:
            fn = stack.pop()
            for nxt in (*fn.nested.values(),
                        *(s.callee for s in fn.calls
                          if s.callee is not None and s.callee.cls is cls)):
                if nxt.qname not in seen:
                    seen.add(nxt.qname)
                    stack.append(nxt)
        return seen

    @staticmethod
    def _self_writes(fn):
        for node in iter_scope(fn.node.body):
            targets = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    yield node, t.attr


def _lock_identity(fn, expr: ast.expr) -> str:
    """Stable cross-function identity for a lock expression.

    ``self.<attr>`` resolves through the owning class
    (``Engine._queue_stats_lock``); ``self.<attr>.<leaf>`` resolves the
    middle attribute's inferred class (``KvAllocator._lock``); plain
    names stay as written (module-level locks). Call expressions
    (``self._lock_for(k)``) keep their dotted text plus ``()`` so keyed
    lock factories compare by factory, not by instance."""
    suffix = ""
    if isinstance(expr, ast.Call):
        expr = expr.func
        suffix = "()"
    qn = qualified_name(expr)
    if not qn:
        return ""
    parts = qn.split(".")
    if parts[0] in ("self", "cls") and fn.cls is not None:
        if len(parts) >= 3:
            attr_cls = fn.cls.attr_types.get(parts[1])
            if attr_cls is not None:
                return f"{attr_cls.name}.{'.'.join(parts[2:])}{suffix}"
        return f"{fn.cls.name}.{'.'.join(parts[1:])}{suffix}"
    return qn + suffix


class LockOrderInversion(CallGraphRule):
    rule_id = "lock-order-inversion"
    description = ("two locks are acquired in both orders across the "
                   "project (directly nested `with` blocks or "
                   "transitively through callees): a deadlock window "
                   "between the engine thread and the event loop")

    _MAX_PATH = 4

    def check_graph(self, graph) -> Iterable[Finding]:
        own = self._own_acquires(graph)
        trans = self._transitive_acquires(graph, own)
        orders = self._order_edges(graph, own, trans)
        for a, b in sorted(orders):
            if a >= b or (b, a) not in orders:
                continue
            module, line, col, chain_ab = orders[(a, b)]
            _m2, _l2, _c2, chain_ba = orders[(b, a)]
            yield Finding(
                module.path, line, col, self.rule_id,
                f"locks `{a}` and `{b}` are acquired in both orders: "
                "two threads taking them concurrently can deadlock",
                "pick one global acquisition order (document it where "
                "the locks are defined), or copy the data out under the "
                "first lock and take the second one afterwards",
                chain=(*chain_ab, "⇄", *chain_ba))

    @classmethod
    def _own_acquires(cls, graph) -> dict:
        """qname -> {lock_id: (line, path)} acquired in the function's
        own scope."""
        out: dict = {}
        for fn in graph.functions.values():
            locks: dict = {}
            for node in iter_scope(fn.node.body):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                for item in node.items:
                    if not _looks_like_lock(item.context_expr):
                        continue
                    lock = _lock_identity(fn, item.context_expr)
                    if lock:
                        locks.setdefault(
                            lock,
                            (node.lineno,
                             (f"{fn.display}:{node.lineno}",)))
            out[fn.qname] = locks
        return out

    @classmethod
    def _transitive_acquires(cls, graph, own: dict) -> dict:
        """qname -> {lock_id: (line, path)}: locks acquired by the
        function or anything it (transitively) calls."""
        trans = {q: dict(locks) for q, locks in own.items()}
        changed = True
        passes = 0
        while changed and passes < 20:
            changed = False
            passes += 1
            for fn in graph.functions.values():
                mine = trans[fn.qname]
                for site in fn.calls:
                    callee = site.callee
                    if callee is None or callee.qname == fn.qname:
                        continue
                    for lock, (_line, path) in trans[callee.qname].items():
                        if lock in mine:
                            continue
                        mine[lock] = (
                            site.line,
                            (f"{fn.display}:{site.line}",
                             *path)[: cls._MAX_PATH])
                        changed = True
        return trans

    @classmethod
    def _order_edges(cls, graph, own: dict, trans: dict) -> dict:
        """(held, acquired) -> (module, line, col, witness chain) for
        every acquisition-order edge witnessed in the project."""
        orders: dict = {}

        def record(pair, module, line, col, chain):
            orders.setdefault(pair, (module, line, col, tuple(chain)))

        for fn in graph.functions.values():
            sites = {id(s.node): s for s in fn.calls}
            for node in iter_scope(fn.node.body):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                held = []
                for item in node.items:
                    if not _looks_like_lock(item.context_expr):
                        continue
                    lock = _lock_identity(fn, item.context_expr)
                    if not lock:
                        continue
                    for prev in held:
                        record((prev, lock), fn.module, node.lineno,
                               node.col_offset,
                               (f"{fn.display}:{node.lineno} holds "
                                f"`{prev}`", f"acquires `{lock}`"))
                    held.append(lock)
                if not held:
                    continue
                for sub in iter_scope(node.body):
                    inner: dict = {}
                    if isinstance(sub, (ast.With, ast.AsyncWith)):
                        for item in sub.items:
                            if _looks_like_lock(item.context_expr):
                                lk = _lock_identity(fn, item.context_expr)
                                if lk:
                                    inner[lk] = (
                                        sub.lineno,
                                        (f"{fn.display}:{sub.lineno}",))
                    elif isinstance(sub, ast.Call) and id(sub) in sites:
                        callee = sites[id(sub)].callee
                        if callee is not None:
                            inner = trans.get(callee.qname, {})
                    for lock, (_line, path) in inner.items():
                        for prev in held:
                            if lock == prev:
                                continue
                            record((prev, lock), fn.module, sub.lineno,
                                   getattr(sub, "col_offset", 0),
                                   (f"{fn.display}:{node.lineno} holds "
                                    f"`{prev}`", *path,
                                    f"acquires `{lock}`"))
        return orders
