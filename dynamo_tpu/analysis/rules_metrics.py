"""direct-prometheus-import rule: every metric goes through the registry.

``runtime/metrics.py`` is the single chokepoint where series get their
hierarchy labels and where same-name/different-shape registrations fail
fast with a ``ValueError`` (instead of prometheus_client's confusing
labels() error at call time, far from the bug). A module that imports
``prometheus_client`` directly bypasses all of that: its series skip the
``dynamo_tpu_`` prefix convention, the hierarchy labels dashboards join
on, and the label/name collision checks — and silently lands in the
DEFAULT prometheus registry, which ``/metrics`` never serves. This rule
makes the chokepoint a lint invariant: ``prometheus_client`` may only be
imported by ``runtime/metrics.py``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import Finding, Module, Rule

_ALLOWED_SUFFIX = "runtime/metrics.py"
_TARGET = "prometheus_client"


class DirectPrometheusImport(Rule):
    rule_id = "direct-prometheus-import"
    description = ("prometheus_client may only be imported by "
                   "runtime/metrics.py — every series must go through "
                   "MetricsRegistry so it gets the dynamo_tpu_ prefix, "
                   "hierarchy labels, name/label collision checks, and "
                   "actually appears in /metrics exposition")

    def check(self, module: Module) -> Iterable[Finding]:
        if module.norm_path.endswith(_ALLOWED_SUFFIX):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name == _TARGET or name.startswith(_TARGET + "."):
                    yield self.finding(
                        module, node,
                        f"direct `{_TARGET}` import outside "
                        "runtime/metrics.py: series created here bypass "
                        "the registry's prefix/hierarchy-label/collision "
                        "checks and never reach /metrics",
                        "construct the metric through a MetricsRegistry "
                        "node (runtime.metrics) instead")
                    break
