"""Wire-error-taxonomy rule: typed errors must survive the request plane.

The request plane serializes handler exceptions to a string frame
(`{"t": "err", "e": ...}`). A typed error class keeps its identity across
that hop only if THREE places agree:

  1. the class (runtime/errors.py) declares a ``WIRE_PREFIX``,
  2. the server error handler (runtime/service.py) encodes it —
     references ``Cls.WIRE_PREFIX`` when building the err frame,
  3. the client decoder (runtime/client.py) decodes it — references
     ``Cls.WIRE_PREFIX`` and re-raises the class.

Round-5 ADVICE is the motivating failure: engine-raised OverloadedError
had no prefix, arrived remotely as generic EngineError, and the frontend
answered 500 instead of 503 — silently breaking router retry in exactly
(and only) distributed deployments. This rule makes that drift a lint
failure: any EngineError subclass raised from engine-side code
(dynamo_tpu/engine/, dynamo_tpu/llm/) must carry a WIRE_PREFIX that both
service.py and client.py reference.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import (
    Finding, Module, ProjectRule, qualified_name)

_ERRORS_SUFFIX = "runtime/errors.py"
_SERVICE_SUFFIX = "runtime/service.py"
_CLIENT_SUFFIX = "runtime/client.py"
_ROOT_CLASS = "EngineError"
# Modules on the handler side of the plane: errors raised here cross the
# wire back to the client decoder. backends/ (the worker mains) joined
# when the SetRole control verb landed: RoleTransitionError surfaces
# from role-manager plumbing the worker mains own (llm/reconfig.py,
# backends/*.py), and a control-verb rejection that degrades to a
# generic 500 remotely is exactly the drift this rule exists to catch.
_ENGINE_SIDE = ("/engine/", "/llm/", "/backends/")


class WireErrorTaxonomy(ProjectRule):
    rule_id = "wire-error-taxonomy"
    description = ("every EngineError subclass raised by engine-side code "
                   "(engine/, llm/, backends/) needs a WIRE_PREFIX encoded "
                   "in runtime/service.py and decoded in runtime/client.py, "
                   "so HTTP status and retry semantics survive remote "
                   "deployment")

    def check_project(self, modules: list[Module]) -> Iterable[Finding]:
        errors_mod = self._find(modules, _ERRORS_SUFFIX)
        service_mod = self._find(modules, _SERVICE_SUFFIX)
        client_mod = self._find(modules, _CLIENT_SUFFIX)
        if errors_mod is None:
            return  # partial run without the taxonomy: nothing to check
        classes, prefixed = self._error_classes(errors_mod)
        raised = self._engine_side_raises(modules, classes)
        service_refs = (self._wire_prefix_refs(service_mod)
                        if service_mod else None)
        client_refs = (self._wire_prefix_refs(client_mod)
                       if client_mod else None)

        for cls, (mod, node) in sorted(raised.items()):
            if cls not in prefixed:
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.rule_id,
                    f"`{cls}` is raised by engine-side code but declares no "
                    "WIRE_PREFIX: remotely it degrades to generic "
                    "EngineError (HTTP 500, no retry)",
                    f"add `WIRE_PREFIX = \"...\"` to {cls} and wire it "
                    "through service.py encode + client.py decode")
        for cls in sorted(prefixed):
            line = classes[cls]
            for refs, mod, role in ((service_refs, service_mod, "encoded"),
                                    (client_refs, client_mod, "decoded")):
                if refs is not None and cls not in refs:
                    yield Finding(
                        errors_mod.path, line, 0, self.rule_id,
                        f"`{cls}.WIRE_PREFIX` is declared but never "
                        f"{role} in {mod.norm_path}: the typed error "
                        "cannot survive the request plane",
                        f"reference `{cls}.WIRE_PREFIX` in the "
                        f"{'error handler' if role == 'encoded' else 'stream decoder'}")

    @staticmethod
    def _find(modules: list[Module], suffix: str) -> Module | None:
        for m in modules:
            if m.norm_path.endswith(suffix):
                return m
        return None

    @staticmethod
    def _error_classes(errors_mod: Module) -> tuple[dict[str, int], set[str]]:
        """EngineError subclasses (name -> def line) and which of them
        declare a string WIRE_PREFIX."""
        bases: dict[str, list[str]] = {}
        lines: dict[str, int] = {}
        has_prefix: set[str] = set()
        for node in errors_mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            bases[node.name] = [qualified_name(b) for b in node.bases]
            lines[node.name] = node.lineno
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "WIRE_PREFIX"
                        for t in stmt.targets):
                    has_prefix.add(node.name)
        # transitive closure down from the root class
        family = {_ROOT_CLASS}
        changed = True
        while changed:
            changed = False
            for name, bs in bases.items():
                if name not in family and any(b in family for b in bs):
                    family.add(name)
                    changed = True
        classes = {n: lines[n] for n in family if n in lines}
        return classes, has_prefix & set(classes)

    @staticmethod
    def _engine_side_raises(modules: list[Module], classes: dict[str, int]
                            ) -> dict[str, tuple[Module, ast.AST]]:
        """class name -> first engine-side raise site."""
        raised: dict[str, tuple[Module, ast.AST]] = {}
        for mod in modules:
            path = mod.norm_path
            if not any(seg in path for seg in _ENGINE_SIDE):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = (node.exc.func if isinstance(node.exc, ast.Call)
                       else node.exc)
                name = qualified_name(exc).rsplit(".", 1)[-1]
                if name in classes and name not in raised:
                    raised[name] = (mod, node)
        return raised

    @staticmethod
    def _wire_prefix_refs(mod: Module) -> set[str]:
        """Class names X for every `X.WIRE_PREFIX` attribute reference."""
        refs: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "WIRE_PREFIX":
                base = qualified_name(node.value).rsplit(".", 1)[-1]
                if base:
                    refs.add(base)
        return refs
