"""Content-hash run cache for ``run_analysis``.

The full-repo pass (parse → call graph → dataflow → 18 rules) costs a
few seconds; the warm ``scripts/check.sh`` lint stage should cost
milliseconds when nothing changed. This cache stores the *result* of a
run (findings, suppression counts, stats, timings) keyed by:

- the **engine hash** — sha256 over every ``dynamo_tpu/analysis/*.py``
  source file, so editing any rule, the dataflow engine, or this cache
  invalidates everything;
- the **per-file content hashes** of every analyzed source file;
- the selected rule ids;
- **today's date** — suppression expiry (``until=YYYY-MM-DD``) makes
  results date-dependent, so a cached clean run can't mask a
  suppression that expired overnight.

Whole-run granularity is deliberate: dataflow summaries and call-graph
facts are interprocedural, so reusing one file's facts while a
dependency changed would be unsound. The per-file hashes in the key
give exact invalidation; any change recomputes everything (still <10s).

Entries live under ``.dtpu-lint-cache/`` (gitignored); the newest
few are kept, the rest pruned. ``--no-cache`` bypasses entirely; the
API default is cache-off so tests and library callers never touch the
working tree.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Iterable

__all__ = ["engine_hash", "run_key", "expand_files", "load_run",
           "store_run", "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = ".dtpu-lint-cache"
_KEEP = 8
_engine_hash: str | None = None


def engine_hash() -> str:
    """sha256 over the analyzer's own sources — the engine version."""
    global _engine_hash
    if _engine_hash is None:
        h = hashlib.sha256()
        pkg = Path(__file__).resolve().parent
        for f in sorted(pkg.glob("*.py")):
            h.update(f.name.encode())
            h.update(f.read_bytes())
        _engine_hash = h.hexdigest()
    return _engine_hash


def expand_files(paths: Iterable[str | Path]) -> list[Path]:
    """The same file expansion load_paths performs, for hashing."""
    files: list[Path] = []
    for raw in paths:
        p = Path(raw)
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    return files


def run_key(files: list[Path], select, today: str) -> str:
    h = hashlib.sha256()
    h.update(engine_hash().encode())
    h.update(today.encode())
    h.update(repr(sorted(select) if select else None).encode())
    for f in files:
        h.update(str(f).encode())
        try:
            h.update(hashlib.sha256(f.read_bytes()).digest())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()


def load_run(cache_dir: str | Path, key: str) -> dict | None:
    path = Path(cache_dir) / f"run-{key[:32]}.json"
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    if doc.get("key") != key:
        return None
    return doc


def store_run(cache_dir: str | Path, key: str, doc: dict) -> None:
    root = Path(cache_dir)
    try:
        root.mkdir(parents=True, exist_ok=True)
        out = dict(doc)
        out["key"] = key
        path = root / f"run-{key[:32]}.json"
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(out, sort_keys=True), encoding="utf-8")
        tmp.replace(path)
        entries = sorted(root.glob("run-*.json"),
                         key=lambda p: p.stat().st_mtime, reverse=True)
        for stale in entries[_KEEP:]:
            stale.unlink(missing_ok=True)
    except OSError:
        # cache failures must never fail the lint run
        return
