"""JAX compilation-hygiene rules.

`jax.jit` returns a *new* compiled-callable cache every time it is
called: constructing it per request / per step / per loop iteration
recompiles (seconds of XLA time) on the serving hot path. The repo
idiom is to build jitted programs once — at module scope, in
``__init__``, or memoized into a cache dict keyed by shape bucket
(engine/runner.py `_window_cache`) — and this rule enforces exactly
that shape.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import Finding, Module, Rule, qualified_name

_JIT_QUALS = {"jax.jit", "jit"}
_PARTIAL_QUALS = {"functools.partial", "partial"}
_INIT_METHODS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}


def _is_jit_ctor(call: ast.Call) -> bool:
    qual = qualified_name(call.func)
    if qual in _JIT_QUALS:
        return True
    return (qual in _PARTIAL_QUALS and call.args
            and qualified_name(call.args[0]) in _JIT_QUALS)


class JitRecompileHazard(Rule):
    rule_id = "jit-recompile-hazard"
    description = ("`jax.jit` constructed inside a function or loop without "
                   "being cached: every call recompiles; also flags "
                   "unhashable static_argnums/static_argnames specs")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _is_jit_ctor(node):
                yield from self._check_static_spec(module, node)
                yield from self._check_scope(module, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # bare `@jax.jit` decorator (a Name/Attribute, not a Call)
                # on a def nested inside a function re-decorates — and
                # recompiles — on every outer call.
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call) \
                            and qualified_name(dec) in _JIT_QUALS:
                        outer = module.enclosing_function(node)
                        oname = getattr(outer, "name", "<lambda>") \
                            if outer is not None else None
                        if oname is not None and oname not in _INIT_METHODS:
                            yield self.finding(
                                module, dec,
                                f"`@jax.jit` on nested function "
                                f"`{node.name}` inside `{oname}`: "
                                "recompiles on every outer call",
                                "hoist the jitted function to module "
                                "scope or cache the compiled callable")

    # -- unhashable static specs ---------------------------------------------
    def _check_static_spec(self, module: Module,
                           call: ast.Call) -> Iterable[Finding]:
        for kw in call.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            if isinstance(kw.value, (ast.List, ast.Set, ast.Dict)):
                yield self.finding(
                    module, kw.value,
                    f"`{kw.arg}` given a mutable "
                    f"{type(kw.value).__name__.lower()} display: jit cache "
                    "keys must be hashable and the spec should be a "
                    "tuple/int/str constant",
                    "use a tuple of int/str constants")

    # -- construction scope ---------------------------------------------------
    def _check_scope(self, module: Module,
                     call: ast.Call) -> Iterable[Finding]:
        parent = module.parent(call)
        # partial(jax.jit, ...) used purely as a decorator piece: judge
        # the partial call (our caller walks every Call, so the inner
        # jax.jit Name isn't a Call and only the partial arrives here).
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and call in parent.decorator_list:
            # decorator on a def: hazardous only when that def is itself
            # nested inside a function (re-decorated per outer call).
            outer = module.enclosing_function(parent)
            if outer is not None:
                name = getattr(outer, "name", "<lambda>")
                if name not in _INIT_METHODS:
                    yield self.finding(
                        module, call,
                        f"`@jit` decorator on nested function "
                        f"`{parent.name}` inside `{name}`: recompiles on "
                        "every outer call",
                        "hoist the jitted function to module scope or "
                        "cache the compiled callable")
            return
        fn = module.enclosing_function(call)
        if fn is None:
            return  # module / class scope: compiled once at import
        name = getattr(fn, "name", "<lambda>")
        if name in _INIT_METHODS:
            return  # compiled once per instance, the repo idiom
        loop = self._enclosing_loop(module, call, fn)
        if loop is not None:
            yield self.finding(
                module, call,
                f"`jax.jit` constructed inside a {type(loop).__name__} "
                f"loop in `{name}`: recompiles every iteration",
                "hoist construction out of the loop (memoize by shape "
                "bucket if specialization is needed)")
            return
        if not self._is_cached(module, call, fn):
            yield self.finding(
                module, call,
                f"`jax.jit` constructed in `{name}` without caching the "
                "compiled callable: every call to the function recompiles",
                "assign the result to an attribute / cache dict "
                "(cf. runner.py _window_cache), or build it in __init__")

    @staticmethod
    def _enclosing_loop(module: Module, node: ast.AST, fn: ast.AST):
        n = module.parent(node)
        while n is not None and n is not fn:
            if isinstance(n, (ast.For, ast.While, ast.AsyncFor)):
                return n
            n = module.parent(n)
        return None

    @staticmethod
    def _is_cached(module: Module, call: ast.Call, fn) -> bool:
        """The jit result escapes into instance/cache storage: directly
        assigned to an Attribute/Subscript target, or assigned to a local
        that is itself stored into an Attribute/Subscript somewhere in
        the same function (`fn = jax.jit(...); self._cache[key] = fn`)."""
        node: ast.AST = call
        parent = module.parent(node)
        # unwrap trivial wrappers between the jit call and the statement
        while isinstance(parent, (ast.IfExp,)):
            node, parent = parent, module.parent(parent)
        if isinstance(parent, ast.Call) and parent.func is node:
            return False  # jax.jit(f)(...): compiles per invocation
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = (parent.targets if isinstance(parent, ast.Assign)
                       else [parent.target])
            local_names = set()
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return True
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
            if local_names:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Assign) \
                            and isinstance(sub.value, ast.Name) \
                            and sub.value.id in local_names \
                            and any(isinstance(t, (ast.Attribute, ast.Subscript))
                                    for t in sub.targets):
                        return True
        return False


class UnregisteredJit(Rule):
    """The perf plane's compile observatory (engine/perf.py
    CompileRegistry) only sees programs built through
    ``perf.instrumented_jit`` — a raw ``jax.jit`` call site is a dark
    program: its compiles never reach ``perf_compiles_total``, and the
    unexpected-recompile detector (the runtime twin of
    jit-recompile-hazard) cannot watch it. One-shot jits that never
    dispatch from the serving loop (e.g. runner._mh_zeros pool
    creation) carry a justified suppression instead."""

    rule_id = "unregistered-jit"
    description = ("`jax.jit` call site outside engine/perf.py: serving "
                   "programs must be built through perf.instrumented_jit "
                   "so the compile observatory counts their compiles and "
                   "the unexpected-recompile detector watches them")

    _ALLOWED_SUFFIX = "engine/perf.py"

    def check(self, module: Module) -> Iterable[Finding]:
        if module.norm_path.endswith(self._ALLOWED_SUFFIX):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _is_jit_ctor(node):
                yield self.finding(
                    module, node,
                    "`jax.jit` outside engine/perf.py: this program is "
                    "invisible to the compile observatory "
                    "(perf_compiles_total, unexpected-recompile detector)",
                    "build it with perf.instrumented_jit(program, fn, "
                    "key=<shape key>, **jit_kwargs); suppress only for "
                    "one-shot jits that never dispatch from the serving "
                    "loop")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # bare `@jax.jit` decorator creates an unregistered
                # program just the same.
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call) \
                            and qualified_name(dec) in _JIT_QUALS:
                        yield self.finding(
                            module, dec,
                            f"`@jax.jit` on `{node.name}` outside "
                            "engine/perf.py: this program is invisible to "
                            "the compile observatory",
                            "wrap with perf.instrumented_jit instead of "
                            "the bare decorator")
