"""Async-correctness rules: the event loop and task-lifetime hazards that
review keeps missing in a 245-coroutine codebase.

All four rules only consider code whose *nearest* enclosing function is an
``async def`` — a sync helper thread defined inside an async module (the
KV plane's socket loops, the engine thread) is free to block.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.callgraph import BLOCKING_CALLS as _BLOCKING_CALLS
from dynamo_tpu.analysis.core import (
    CallGraphRule, Finding, Module, Rule, iter_scope, qualified_name)

_QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                "queue.SimpleQueue", "Queue", "LifoQueue", "PriorityQueue",
                "SimpleQueue"}


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_false(node: ast.expr | None) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


class BlockingCallInAsync(CallGraphRule):
    rule_id = "blocking-call-in-async"
    description = ("Synchronous blocking call (sleep, subprocess, socket, "
                   "file or thread-queue I/O, Future.result, "
                   "block_until_ready) inside `async def` parks the event "
                   "loop — directly, or transitively through a sync helper "
                   "that blocks frames below the call site")

    def check_graph(self, graph) -> Iterable[Finding]:
        for mi in graph.modules:
            yield from self.check(mi.module)
        # Interprocedural part: an async def calling a *sync* project
        # function that (transitively) blocks parks the loop exactly the
        # same — flagged at the call site, with the propagation chain.
        for fn in graph.functions.values():
            if not fn.is_async:
                continue
            for site in fn.calls:
                c = site.callee
                if c is None or c.is_async or not c.blocks:
                    continue
                chain = [fn.display] + graph.blocking_chain(c)
                yield Finding(
                    fn.module.path, site.node.lineno, site.node.col_offset,
                    self.rule_id,
                    f"`{site.raw}(...)` called from async `{fn.node.name}` "
                    f"blocks the event loop {len(chain) - 2} frame(s) down "
                    f"(leaf: `{chain[-1]}`)",
                    "await an async variant, or move the blocking helper "
                    "behind `asyncio.to_thread`/`run_in_executor`",
                    chain=tuple(chain))

    def check(self, module: Module) -> Iterable[Finding]:
        thread_queues = self._thread_queues(module)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            task_names = self._async_future_names(fn)
            for node in iter_scope(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                qual = qualified_name(node.func)
                if qual in _BLOCKING_CALLS:
                    yield self.finding(
                        module, node,
                        f"blocking call `{qual}(...)` inside async "
                        f"function `{fn.name}`",
                        _BLOCKING_CALLS[qual])
                    continue
                if qual == "open":
                    yield self.finding(
                        module, node,
                        f"synchronous file I/O `open(...)` inside async "
                        f"function `{fn.name}`",
                        "move the I/O into `asyncio.to_thread`/"
                        "`run_in_executor`, or suppress with a rationale "
                        "if it is one-shot startup I/O")
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                leaf = node.func.attr
                recv = qualified_name(node.func.value)
                if leaf == "block_until_ready":
                    yield self.finding(
                        module, node,
                        f"`{recv}.block_until_ready()` blocks the event "
                        f"loop on device completion in `{fn.name}`",
                        "dispatch, then await the result via "
                        "`asyncio.to_thread` or poll with async sleeps")
                elif leaf == "result" and not node.args and not node.keywords:
                    # .result() with a timeout is concurrent.futures-style
                    # blocking wait; argless on an asyncio task/future it
                    # is a non-blocking fetch — skip receivers we saw
                    # created via create_task/ensure_future.
                    if recv not in task_names:
                        yield self.finding(
                            module, node,
                            f"`{recv}.result()` may block the event loop "
                            f"in `{fn.name}` (concurrent.futures wait)",
                            "await the future (`await asyncio.wrap_future"
                            "(...)`) or confirm it is an already-completed "
                            "asyncio task and suppress")
                elif leaf == "result" and (node.args or node.keywords):
                    yield self.finding(
                        module, node,
                        f"`{recv}.result(timeout)` blocks the event loop "
                        f"in `{fn.name}`",
                        "await the future instead")
                elif leaf == "get" and recv in thread_queues:
                    if not _is_false(_kw(node, "block")):
                        yield self.finding(
                            module, node,
                            f"thread-queue `{recv}.get()` blocks the event "
                            f"loop in `{fn.name}`",
                            "use get_nowait()+retry, asyncio.Queue, or "
                            "`asyncio.to_thread`")
                elif leaf == "put" and recv in thread_queues:
                    if thread_queues[recv] and not _is_false(_kw(node, "block")):
                        yield self.finding(
                            module, node,
                            f"bounded thread-queue `{recv}.put()` can block "
                            f"the event loop in `{fn.name}`",
                            "use put_nowait() with a drop/backpressure "
                            "policy, or `asyncio.to_thread`")

    @staticmethod
    def _thread_queues(module: Module) -> dict[str, bool]:
        """Receiver qual -> bounded? for every `x = queue.Queue(...)` /
        `self.x = queue.Queue(maxsize=...)` assignment in the module."""
        queues: dict[str, bool] = {}
        for node in ast.walk(module.tree):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if not isinstance(value, ast.Call):
                continue
            if qualified_name(value.func) not in _QUEUE_CTORS:
                continue
            size = value.args[0] if value.args else _kw(value, "maxsize")
            bounded = size is not None and not (
                isinstance(size, ast.Constant) and not size.value)
            for t in targets:
                name = qualified_name(t)
                if name:
                    queues[name] = bounded
        return queues

    @staticmethod
    def _async_future_names(fn: ast.AsyncFunctionDef) -> set[str]:
        """Local names bound to asyncio tasks/futures (create_task /
        ensure_future) — their argless .result() is non-blocking."""
        names: set[str] = set()
        for node in iter_scope(fn.body):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                qual = qualified_name(node.value.func)
                if qual.rsplit(".", 1)[-1] in ("create_task", "ensure_future"):
                    for t in node.targets:
                        name = qualified_name(t)
                        if name:
                            names.add(name)
        return names


class FireAndForgetTask(Rule):
    rule_id = "fire-and-forget-task"
    description = ("`asyncio.create_task`/`ensure_future` whose result is "
                   "discarded — the event loop keeps only a weak reference, "
                   "so the task can be garbage-collected mid-flight")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            qual = qualified_name(node.value.func)
            if qual.rsplit(".", 1)[-1] in ("create_task", "ensure_future"):
                yield self.finding(
                    module, node,
                    f"`{qual}(...)` result discarded: the task holds only "
                    "a weak loop reference and may be GC-cancelled",
                    "store it (self._task = ..., or a task set with "
                    "add_done_callback(set.discard)) or await it")


_LOCKISH = ("lock", "mutex", "sem")


def _looks_like_lock(expr: ast.expr) -> str | None:
    target = expr.func if isinstance(expr, ast.Call) else expr
    qual = qualified_name(target)
    leaf = qual.rsplit(".", 1)[-1].lower()
    if any(k in leaf for k in _LOCKISH):
        return qual
    return None


class LockAcrossAwait(Rule):
    rule_id = "lock-across-await"
    description = ("`await` inside a synchronous `with <lock>` block: the "
                   "coroutine suspends while holding a thread lock, "
                   "deadlocking every thread (and coroutine) that needs it")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With) or not module.in_async_scope(node):
                continue
            lock = next((q for item in node.items
                         if (q := _looks_like_lock(item.context_expr))), None)
            if lock is None:
                continue
            for sub in iter_scope(node.body):
                if isinstance(sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                    yield self.finding(
                        module, sub,
                        f"await while holding `{lock}` (acquired line "
                        f"{node.lineno}): the lock stays held across the "
                        "suspension",
                        "release before awaiting, or use asyncio.Lock with "
                        "`async with`")
                    break


class UnboundedWait(Rule):
    rule_id = "unbounded-wait"
    description = ("`await` on an event/reply with no deadline — "
                   "`await x.wait()` or awaiting a `create_future()` "
                   "future directly. A lost wakeup or reply frame parks "
                   "the caller forever; wrap in `asyncio.wait_for(...)` "
                   "or suppress serve-forever waits with a rationale")

    def check(self, module: Module) -> Iterable[Finding]:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            future_names = self._created_future_names(fn)
            for node in iter_scope(fn.body):
                if not isinstance(node, ast.Await):
                    continue
                value = node.value
                # `await x.wait()` — an argless event-style wait not
                # wrapped in wait_for (the wrapper makes the await's
                # value the wait_for call itself, so it never matches).
                if (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr == "wait"
                        and not value.args and not value.keywords):
                    recv = qualified_name(value.func.value)
                    yield self.finding(
                        module, node,
                        f"unbounded `await {recv}.wait()` in `{fn.name}`: "
                        "a lost wakeup parks this caller forever",
                        "wrap in `asyncio.wait_for(..., timeout)` (or "
                        "suppress if waiting forever is the contract, "
                        "e.g. serve-forever loops)")
                # `await fut` where fut came from create_future() in
                # this function — a reply future nobody is obligated to
                # resolve (the resolver may die with the connection).
                elif (isinstance(value, ast.Name)
                      and value.id in future_names):
                    yield self.finding(
                        module, node,
                        f"unbounded `await {value.id}` on a "
                        f"create_future() reply future in `{fn.name}`",
                        "wrap in `asyncio.wait_for(..., timeout)` so a "
                        "lost reply becomes a typed error, not a hang")

    @staticmethod
    def _created_future_names(fn: ast.AsyncFunctionDef) -> set[str]:
        names: set[str] = set()
        for node in iter_scope(fn.body):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if (isinstance(value, ast.Call)
                    and qualified_name(value.func).endswith("create_future")):
                for t in targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names


_ASYNC_QUEUE_CTORS = {"asyncio.Queue", "asyncio.LifoQueue",
                      "asyncio.PriorityQueue"}


class UnboundedQueue(Rule):
    rule_id = "unbounded-queue"
    description = ("`asyncio.Queue()` constructed without a maxsize outside "
                   "test code: under overload it buffers arrivals "
                   "unboundedly — memory grows and every queued item's "
                   "latency is already blown before service starts. Bound "
                   "it (with a shed/backpressure policy for the full case) "
                   "or suppress with the rationale that bounds it naturally")

    def check(self, module: Module) -> Iterable[Finding]:
        parts = module.norm_path.split("/")
        # Test code is exempt: tests build throwaway queues where the
        # producer is the test itself.
        if "tests" in parts[:-1] or parts[-1].startswith("test_"):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if qualified_name(node.func) not in _ASYNC_QUEUE_CTORS:
                continue
            size = node.args[0] if node.args else _kw(node, "maxsize")
            if size is not None and not (isinstance(size, ast.Constant)
                                         and not size.value):
                continue
            yield self.finding(
                module, node,
                f"`{qualified_name(node.func)}()` without maxsize: "
                "unbounded buffering under overload",
                "pass maxsize= (pair put_nowait with a QueueFull "
                "shed/backpressure policy), or suppress with the "
                "invariant that bounds the queue (e.g. one item per "
                "in-flight request capped elsewhere)")


_CANCELLED = {"asyncio.CancelledError", "CancelledError"}


def _catches_cancellation(type_node: ast.expr | None) -> bool:
    """Bare except / BaseException / explicit CancelledError inside a
    tuple. A lone `except Exception` does NOT catch CancelledError on
    py>=3.8 and a lone explicit CancelledError handler is intentional."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(qualified_name(e) in _CANCELLED | {"BaseException"}
                   for e in type_node.elts)
    return qualified_name(type_node) == "BaseException"


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in iter_scope(handler.body):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            exc = node.exc.func if isinstance(node.exc, ast.Call) else node.exc
            name = qualified_name(exc)
            if name == handler.name or name in _CANCELLED:
                return True
    return False


class SwallowedCancellation(Rule):
    rule_id = "swallowed-cancellation"
    description = ("except clause in async code that catches "
                   "`asyncio.CancelledError` (bare / BaseException / tuple "
                   "membership) without re-raising — cancellation never "
                   "terminates the task")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Try) or not module.in_async_scope(node):
                continue
            if not any(isinstance(s, ast.Await) for s in iter_scope(node.body)):
                continue  # nothing cancellable inside the try
            for handler in node.handlers:
                if (_catches_cancellation(handler.type)
                        and not _reraises(handler)):
                    what = ("bare `except:`" if handler.type is None else
                            f"`except {ast.unparse(handler.type)}`")
                    yield self.finding(
                        module, handler,
                        f"{what} swallows asyncio.CancelledError around an "
                        "await: task cancellation (shutdown, kill) is "
                        "silently absorbed",
                        "re-raise CancelledError (bare `raise`) or narrow "
                        "the clause to `except Exception`")
