"""dtpu-lint: repo-native static analysis for async/JAX/wire hazards.

v2 is interprocedural: a project-wide symbol table and call graph
(``callgraph.py``) feed transitive facts — async-context, blocking-ness,
hot-path reachability — to the rules, and findings carry the
propagation chain (``engine._dispatch_window → runner.decode_window →
np.asarray``).

Usage (CLI): ``python -m dynamo_tpu.analysis [paths] [--format json]
[--budget deploy/lint-budget.json] [--callgraph MODULE] [--stats]``
Usage (API)::

    from dynamo_tpu.analysis import analyze_paths
    findings = analyze_paths(["dynamo_tpu"])

Rule catalog and suppression syntax: docs/ANALYSIS.md.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from dynamo_tpu.analysis.callgraph import CallGraph, build_callgraph
from dynamo_tpu.analysis.core import (
    CallGraphRule, Finding, Module, ProjectRule, Rule, analyze,
    count_suppressions, load_paths)
from dynamo_tpu.analysis.rules_async import (
    BlockingCallInAsync, FireAndForgetTask, LockAcrossAwait,
    SwallowedCancellation, UnboundedQueue, UnboundedWait)
from dynamo_tpu.analysis.rules_hotpath import HostSyncInHotPath
from dynamo_tpu.analysis.rules_jax import JitRecompileHazard, UnregisteredJit
from dynamo_tpu.analysis.rules_journal import UntypedJournalEvent
from dynamo_tpu.analysis.rules_metrics import DirectPrometheusImport
from dynamo_tpu.analysis.rules_purity import ImpureJitProgram
from dynamo_tpu.analysis.rules_threads import EngineThreadSharedState
from dynamo_tpu.analysis.rules_wire import WireErrorTaxonomy

__all__ = [
    "Finding", "Module", "Rule", "ProjectRule", "CallGraphRule", "analyze",
    "load_paths", "CallGraph", "build_callgraph", "count_suppressions",
    "DEFAULT_RULES", "default_rules", "analyze_paths", "run_analysis",
    "AnalysisRun",
]

DEFAULT_RULES: tuple[type[Rule], ...] = (
    BlockingCallInAsync,
    FireAndForgetTask,
    LockAcrossAwait,
    SwallowedCancellation,
    UnboundedQueue,
    UnboundedWait,
    JitRecompileHazard,
    UnregisteredJit,
    HostSyncInHotPath,
    ImpureJitProgram,
    EngineThreadSharedState,
    DirectPrometheusImport,
    UntypedJournalEvent,
    WireErrorTaxonomy,
)


def default_rules(select: Iterable[str] | None = None) -> list[Rule]:
    """Instantiate the rule set, optionally narrowed to specific ids."""
    wanted = None if select is None else set(select)
    rules = [cls() for cls in DEFAULT_RULES]
    if wanted is not None:
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.rule_id in wanted]
    return rules


@dataclasses.dataclass
class AnalysisRun:
    """One full pass: modules parsed once, the call graph built once,
    every rule run over the shared structures."""

    modules: list[Module]
    failed: list[str]
    rules: list[Rule]
    graph: CallGraph | None
    findings: list[Finding]

    def suppression_counts(self) -> dict[str, int]:
        return count_suppressions(self.modules,
                                  [r.rule_id for r in default_rules()])


def run_analysis(paths: Iterable[str],
                 select: Iterable[str] | None = None) -> AnalysisRun:
    """The single-pass engine behind both the CLI and ``analyze_paths``:
    parse each module once, build the call graph at most once, and share
    both across all selected rules."""
    modules, failed = load_paths(paths)
    rules = default_rules(select)
    graph = (build_callgraph(modules)
             if any(isinstance(r, CallGraphRule) for r in rules) else None)
    findings = analyze(modules, rules, graph=graph)
    findings.extend(
        Finding(path, 1, 0, "parse-error", "file could not be parsed")
        for path in failed)
    return AnalysisRun(modules, failed, rules, graph, findings)


def analyze_paths(paths: Iterable[str],
                  select: Iterable[str] | None = None) -> list[Finding]:
    return run_analysis(paths, select).findings
