"""dtpu-lint: repo-native static analysis for async/JAX/wire hazards.

v2 made the analyzer interprocedural: a project-wide symbol table and
call graph (``callgraph.py``) feed transitive facts — async-context,
blocking-ness, hot-path reachability — to the rules, and findings carry
the propagation chain (``engine._dispatch_window → runner.decode_window
→ np.asarray``).

v3 adds *dataflow* (``dataflow.py``): a flow-sensitive abstract
interpretation over a small lattice (traced / per-request / py-scalar /
shape / const) with function summaries, powering the
compile/purity rules (recompile-on-value, weak-type-promotion,
traced-bool-coercion) plus a lockset analysis (lock-order-inversion).
Everything still runs in ONE pass: parse once, one call graph, one
dataflow, all 18 rules share them — and a content-hash run cache
(``cache.py``) makes the warm path sub-second.

Usage (CLI): ``python -m dynamo_tpu.analysis [paths] [--format
text|json|sarif] [--budget deploy/lint-budget.json] [--callgraph
MODULE] [--stats] [--no-cache] [--sarif-out FILE]``
Usage (API)::

    from dynamo_tpu.analysis import analyze_paths
    findings = analyze_paths(["dynamo_tpu"])

Rule catalog and suppression syntax: docs/ANALYSIS.md.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable

from dynamo_tpu.analysis import cache as _cache
from dynamo_tpu.analysis.callgraph import CallGraph, build_callgraph
from dynamo_tpu.analysis.core import (
    CallGraphRule, Finding, Module, ProjectRule, Rule, _today, analyze,
    count_suppressions, load_paths)
from dynamo_tpu.analysis.dataflow import ensure_dataflow
from dynamo_tpu.analysis.rules_async import (
    BlockingCallInAsync, FireAndForgetTask, LockAcrossAwait,
    SwallowedCancellation, UnboundedQueue, UnboundedWait)
from dynamo_tpu.analysis.rules_dataflow import (
    RecompileOnValue, TracedBoolCoercion, WeakTypePromotion)
from dynamo_tpu.analysis.rules_hotpath import HostSyncInHotPath
from dynamo_tpu.analysis.rules_jax import JitRecompileHazard, UnregisteredJit
from dynamo_tpu.analysis.rules_journal import UntypedJournalEvent
from dynamo_tpu.analysis.rules_metrics import DirectPrometheusImport
from dynamo_tpu.analysis.rules_purity import ImpureJitProgram
from dynamo_tpu.analysis.rules_threads import (
    EngineThreadSharedState, LockOrderInversion)
from dynamo_tpu.analysis.rules_wire import WireErrorTaxonomy

__all__ = [
    "Finding", "Module", "Rule", "ProjectRule", "CallGraphRule", "analyze",
    "load_paths", "CallGraph", "build_callgraph", "count_suppressions",
    "DEFAULT_RULES", "default_rules", "analyze_paths", "run_analysis",
    "AnalysisRun",
]

DEFAULT_RULES: tuple[type[Rule], ...] = (
    BlockingCallInAsync,
    FireAndForgetTask,
    LockAcrossAwait,
    SwallowedCancellation,
    UnboundedQueue,
    UnboundedWait,
    JitRecompileHazard,
    UnregisteredJit,
    HostSyncInHotPath,
    ImpureJitProgram,
    EngineThreadSharedState,
    DirectPrometheusImport,
    UntypedJournalEvent,
    WireErrorTaxonomy,
    RecompileOnValue,
    WeakTypePromotion,
    TracedBoolCoercion,
    LockOrderInversion,
)

# Rules that consume the dataflow substrate (built once, shared).
_DATAFLOW_RULES = (RecompileOnValue, WeakTypePromotion, TracedBoolCoercion)


def default_rules(select: Iterable[str] | None = None) -> list[Rule]:
    """Instantiate the rule set, optionally narrowed to specific ids."""
    wanted = None if select is None else set(select)
    rules = [cls() for cls in DEFAULT_RULES]
    if wanted is not None:
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.rule_id in wanted]
    return rules


@dataclasses.dataclass
class AnalysisRun:
    """One full pass: modules parsed once, the call graph and dataflow
    built once, every rule run over the shared structures.

    ``timings`` isolates analysis cost from I/O (the deflake contract
    for the <10s budget test); ``cached`` marks a run replayed from the
    content-hash cache, in which case the stored suppression counts and
    stats stand in for the unloaded modules/graph."""

    modules: list[Module]
    failed: list[str]
    rules: list[Rule]
    graph: CallGraph | None
    findings: list[Finding]
    timings: dict = dataclasses.field(default_factory=dict)
    cached: bool = False
    cached_suppressions: dict | None = None
    cached_stats: dict | None = None

    def suppression_counts(self) -> dict[str, int]:
        if self.cached_suppressions is not None:
            return dict(self.cached_suppressions)
        return count_suppressions(self.modules,
                                  [r.rule_id for r in default_rules()])

    def graph_stats(self) -> dict:
        if self.graph is not None:
            return self.graph.stats()
        return dict(self.cached_stats or {})


def _run_fresh(paths: Iterable[str],
               select: Iterable[str] | None) -> AnalysisRun:
    timings: dict = {}
    t0 = time.perf_counter()
    c0 = time.thread_time()
    modules, failed = load_paths(paths)
    timings["parse_s"] = time.perf_counter() - t0
    rules = default_rules(select)
    t1 = time.perf_counter()
    graph = (build_callgraph(modules)
             if any(isinstance(r, CallGraphRule) for r in rules) else None)
    timings["graph_s"] = time.perf_counter() - t1
    t2 = time.perf_counter()
    if graph is not None and any(isinstance(r, _DATAFLOW_RULES)
                                 for r in rules):
        ensure_dataflow(graph)
    timings["dataflow_s"] = time.perf_counter() - t2
    t3 = time.perf_counter()
    findings = analyze(modules, rules, graph=graph)
    findings.extend(
        Finding(path, 1, 0, "parse-error", "file could not be parsed")
        for path in failed)
    timings["rules_s"] = time.perf_counter() - t3
    timings["analysis_s"] = time.perf_counter() - t0
    # this thread's CPU seconds: immune to being scheduled out on a
    # saturated box AND to other threads' work — the perf-budget test
    # judges this, not wall time
    timings["analysis_cpu_s"] = time.thread_time() - c0
    return AnalysisRun(modules, failed, rules, graph, findings,
                       timings=timings)


def run_analysis(paths: Iterable[str],
                 select: Iterable[str] | None = None, *,
                 cache_dir: str | None = None) -> AnalysisRun:
    """The single-pass engine behind both the CLI and ``analyze_paths``:
    parse each module once, build the call graph and dataflow at most
    once, and share them across all selected rules.

    ``cache_dir`` enables the content-hash run cache (the CLI passes
    ``.dtpu-lint-cache``; the API default stays cache-off so library
    callers and tests never touch the working tree)."""
    if cache_dir is None:
        return _run_fresh(paths, select)
    files = _cache.expand_files(paths)
    key = _cache.run_key(files, select, _today())
    doc = _cache.load_run(cache_dir, key)
    if doc is not None:
        findings = [Finding(chain=tuple(f.pop("chain", ())), **f)
                    for f in doc["findings"]]
        return AnalysisRun(
            [], doc["failed"], default_rules(select), None, findings,
            timings=dict(doc.get("timings", {})), cached=True,
            cached_suppressions=doc["suppressions"],
            cached_stats=doc["stats"])
    run = _run_fresh(paths, select)
    _cache.store_run(cache_dir, key, {
        "findings": [f.to_json() for f in run.findings],
        "failed": run.failed,
        "suppressions": run.suppression_counts(),
        "stats": run.graph_stats(),
        "timings": run.timings,
    })
    return run


def analyze_paths(paths: Iterable[str],
                  select: Iterable[str] | None = None) -> list[Finding]:
    return run_analysis(paths, select).findings
