"""dtpu-lint: repo-native static analysis for async/JAX/wire hazards.

Usage (CLI): ``python -m dynamo_tpu.analysis [paths] [--json]``
Usage (API)::

    from dynamo_tpu.analysis import analyze_paths
    findings = analyze_paths(["dynamo_tpu"])

Rule catalog and suppression syntax: docs/ANALYSIS.md.
"""

from __future__ import annotations

from typing import Iterable

from dynamo_tpu.analysis.core import (
    Finding, Module, ProjectRule, Rule, analyze, load_paths)
from dynamo_tpu.analysis.rules_async import (
    BlockingCallInAsync, FireAndForgetTask, LockAcrossAwait,
    SwallowedCancellation, UnboundedQueue, UnboundedWait)
from dynamo_tpu.analysis.rules_jax import JitRecompileHazard, UnregisteredJit
from dynamo_tpu.analysis.rules_journal import UntypedJournalEvent
from dynamo_tpu.analysis.rules_metrics import DirectPrometheusImport
from dynamo_tpu.analysis.rules_wire import WireErrorTaxonomy

__all__ = [
    "Finding", "Module", "Rule", "ProjectRule", "analyze", "load_paths",
    "DEFAULT_RULES", "default_rules", "analyze_paths",
]

DEFAULT_RULES: tuple[type[Rule], ...] = (
    BlockingCallInAsync,
    FireAndForgetTask,
    LockAcrossAwait,
    SwallowedCancellation,
    UnboundedQueue,
    UnboundedWait,
    JitRecompileHazard,
    UnregisteredJit,
    DirectPrometheusImport,
    UntypedJournalEvent,
    WireErrorTaxonomy,
)


def default_rules(select: Iterable[str] | None = None) -> list[Rule]:
    """Instantiate the rule set, optionally narrowed to specific ids."""
    wanted = None if select is None else set(select)
    rules = [cls() for cls in DEFAULT_RULES]
    if wanted is not None:
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.rule_id in wanted]
    return rules


def analyze_paths(paths: Iterable[str],
                  select: Iterable[str] | None = None) -> list[Finding]:
    modules, failed = load_paths(paths)
    findings = analyze(modules, default_rules(select))
    findings.extend(
        Finding(path, 1, 0, "parse-error", "file could not be parsed")
        for path in failed)
    return findings
