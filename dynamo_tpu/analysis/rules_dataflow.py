"""Dataflow-backed compile/purity rules: the static twins of the perf
plane's runtime detectors.

Three rules share the :mod:`dynamo_tpu.analysis.dataflow` substrate
(built once per run via ``ensure_dataflow``):

- ``recompile-on-value``: per-request Python data reaching a jit cache
  key or a trace-time position (Python ``if``/format/shape argument)
  inside an ``instrumented_jit`` program body. One compile per distinct
  value — the static twin of ``perf_unexpected_recompiles_total``, and
  the class both PR 9 runtime catches (the uncommitted rng key, the
  per-request penalized window variants) belong to.
- ``weak-type-promotion``: strongly-typed host scalars
  (``np.float32(...)``, dtype-less ``jnp.array`` over Python floats)
  mixed into arithmetic with traced values inside program bodies —
  silently upcasting bf16/int8 paths to f32.
- ``traced-bool-coercion``: ``if``/``while``/``assert``/``and``/``or``/
  ``not`` over traced values inside program bodies —
  ConcretizationTypeError at best, an implicit device→host sync at
  worst (extends host-sync-in-hot-path from explicit transfer calls to
  implicit coercions).

Program bodies are resolved exactly like impure-jit-program resolves
them: the function argument of every ``perf.instrumented_jit(program,
fn, ...)`` call site, looked up through nested scopes then module
functions. Bodies are analyzed *as traced code* (parameters TRACED,
free variables through the builder's environment), nested ``step``
closures included — so builder-time Python branching on config/bucket
booleans stays legal while trace-time branching on traced or
per-request values flags.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import CallGraphRule, Finding, qualified_name
from dynamo_tpu.analysis.dataflow import REQ, TRACED, ensure_dataflow

_NP_ROOTS = {"np", "numpy"}
_NP_SCALAR_CTORS = {"float16", "float32", "float64", "int8", "int16",
                    "int32", "int64", "uint8", "uint16", "uint32",
                    "uint64", "bfloat16"}
_JNP_SHAPE_FNS = {"zeros", "ones", "full", "empty", "arange", "iota",
                  "reshape", "broadcast_to", "tile"}
_TEST_KINDS = {"if": "a Python `if`", "while": "a Python `while`",
               "assert": "an `assert`", "boolop": "an `and`/`or`",
               "not": "a `not`", "ifexp": "a conditional expression"}


def _resolve_program(graph, caller, name: str):
    """The function argument of an instrumented_jit site: a nested def
    in the calling function (the repo idiom), an enclosing function's
    nested def, or a module-level function of the same module."""
    scope = caller
    while scope is not None:
        if name in scope.nested:
            return scope.nested[name]
        scope = scope.parent
    for mi in graph.modules:
        if mi.module is caller.module:
            return mi.functions.get(name)
    return None


def _program_sites(graph):
    """Yield (builder_fn, call_site, body_fn) for every resolvable
    ``instrumented_jit(program, fn, ...)`` call in the project."""
    for caller in graph.functions.values():
        for site in caller.calls:
            if not site.raw.endswith("instrumented_jit") \
                    or len(site.node.args) < 2:
                continue
            arg = site.node.args[1]
            if not isinstance(arg, ast.Name):
                continue
            body = _resolve_program(graph, caller, arg.id)
            if body is not None:
                yield caller, site, body


def _label(node: ast.AST, limit: int = 48) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs
        text = qualified_name(node) or type(node).__name__
    return text if len(text) <= limit else text[: limit - 1] + "…"


class RecompileOnValue(CallGraphRule):
    rule_id = "recompile-on-value"
    description = ("per-request data flows into a jit cache key or a "
                   "trace-time position (Python if/format/shape arg) of "
                   "an instrumented_jit program: one compile per distinct "
                   "value — the static twin of "
                   "perf_unexpected_recompiles_total")

    _HINT_KEY = ("bucket the value before keying (compare/round to a "
                 "bounded set) or pass it into the program as traced "
                 "data instead of baking it into the compile key")
    _HINT_BODY = ("pass the value into the program as data (an argument "
                  "the tracer sees) or hoist the branch to the builder "
                  "over a bounded bucket")

    def check_graph(self, graph) -> Iterable[Finding]:
        df = ensure_dataflow(graph)
        seen: set = set()

        def emit(module, node, message, chain):
            key = (module.path, node.lineno, node.col_offset)
            if key in seen:
                return None
            seen.add(key)
            return Finding(module.path, node.lineno, node.col_offset,
                           self.rule_id, message,
                           self._HINT_BODY if "trace-time" in message
                           else self._HINT_KEY, chain=tuple(chain))

        for fn in graph.functions.values():
            facts = df.facts.get(fn.qname)
            if facts is None:
                continue
            # (a) per-request value directly in a key= at this site
            for call_node, key_expr, av in facts.key_sites:
                if av.base != REQ:
                    continue
                f = emit(fn.module, key_expr,
                         f"per-request value `{' → '.join(av.src)}` is "
                         "part of this jit cache key: every distinct "
                         "value compiles a new program",
                         (fn.display, *av.src, "instrumented_jit(key=…)"))
                if f:
                    yield f
            # (b) per-request actual passed to a param that a callee
            #     summary says reaches a jit key
            for site in fn.calls:
                callee = site.callee
                if callee is None:
                    continue
                summ = df.summaries.get(callee.qname)
                if summ is None or not summ.jit_key_params:
                    continue
                for p, (pname, _line) in sorted(summ.jit_key_params.items()):
                    arg_node = None
                    if p < len(site.node.args):
                        arg_node = site.node.args[p]
                    else:
                        for kw in site.node.keywords:
                            if kw.arg == pname:
                                arg_node = kw.value
                    if arg_node is None:
                        continue
                    av = facts.value(arg_node)
                    if av.base != REQ:
                        continue
                    f = emit(fn.module, arg_node,
                             f"per-request value `{' → '.join(av.src)}` "
                             f"flows into the jit cache key of "
                             f"`{callee.display}` (param `{pname}`): "
                             "every distinct value compiles a new program",
                             (fn.display, *av.src,
                              f"{callee.display}({pname}=…)",
                              "instrumented_jit(key=…)"))
                    if f:
                        yield f

        # (c) per-request closure values at trace-time positions inside
        #     program bodies: Python branches, string formatting, shape
        #     arguments
        for builder, _site, body in _program_sites(graph):
            bf = df.body_facts(body, builder)
            for node, av, kind in bf.tests:
                if av.base != REQ:
                    continue
                f = emit(body.module, node,
                         f"per-request value `{' → '.join(av.src)}` in "
                         f"{_TEST_KINDS.get(kind, 'a branch')} at "
                         "trace-time inside a jitted program: program "
                         "identity depends on the value",
                         (builder.display, body.display, *av.src,
                          f"{kind} {_label(node)}"))
                if f:
                    yield f
            for node, av in bf.joined:
                f = emit(body.module, node,
                         f"per-request value `{' → '.join(av.src)}` "
                         "formatted at trace-time inside a jitted "
                         "program: the string is baked per-value",
                         (builder.display, body.display, *av.src,
                          _label(node)))
                if f:
                    yield f
            for node in ast.walk(body.node):
                if not isinstance(node, ast.Call):
                    continue
                raw = qualified_name(node.func)
                root, _, rest = raw.partition(".")
                if root not in ("jnp", "jax", "lax") \
                        or raw.rsplit(".", 1)[-1] not in _JNP_SHAPE_FNS:
                    continue
                for arg in (*node.args,
                            *(kw.value for kw in node.keywords
                              if kw.arg in ("shape", "newshape"))):
                    av = bf.value(arg)
                    if av.base != REQ:
                        continue
                    f = emit(body.module, arg,
                             f"per-request value "
                             f"`{' → '.join(av.src)}` used as a shape "
                             f"argument of `{raw}` inside a jitted "
                             "program: one compile per distinct shape",
                             (builder.display, body.display, *av.src,
                              f"{raw}(shape)"))
                    if f:
                        yield f


class WeakTypePromotion(CallGraphRule):
    rule_id = "weak-type-promotion"
    description = ("strongly-typed host scalar (np.float32(...), "
                   "dtype-less jnp.array over Python floats) mixed into "
                   "arithmetic with traced values inside a jitted "
                   "program: silently upcasts bf16/int8 paths to f32")

    _HINT = ("use a bare Python literal (weakly typed — preserves the "
             "array's dtype) or give the array an explicit "
             "dtype=x.dtype")

    def check_graph(self, graph) -> Iterable[Finding]:
        df = ensure_dataflow(graph)
        seen: set = set()
        for builder, _site, body in _program_sites(graph):
            bf = df.body_facts(body, builder)
            module = body.module
            for node in ast.walk(body.node):
                if not isinstance(node, ast.Call):
                    continue
                raw = qualified_name(node.func)
                label = None
                if self._np_scalar(raw):
                    label = f"{raw}(…) is a strongly-typed host scalar"
                elif self._dtypeless_float_array(node, raw):
                    label = (f"dtype-less `{raw}` over Python floats "
                             "defaults to strong float32")
                if label is None:
                    continue
                if not self._mixes_with_traced(module, node, bf):
                    continue
                key = (module.path, node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    module.path, node.lineno, node.col_offset,
                    self.rule_id,
                    f"{label}: mixing it into traced arithmetic "
                    "promotes the bf16/int8 operand to f32",
                    self._HINT,
                    chain=(builder.display, body.display, _label(node)))

    @staticmethod
    def _np_scalar(raw: str) -> bool:
        root, _, leaf = raw.rpartition(".")
        return root in _NP_ROOTS and leaf in _NP_SCALAR_CTORS

    @staticmethod
    def _dtypeless_float_array(node: ast.Call, raw: str) -> bool:
        root, _, leaf = raw.rpartition(".")
        if root != "jnp" or leaf not in ("array", "asarray"):
            return False
        if any(kw.arg == "dtype" for kw in node.keywords) \
                or len(node.args) != 1:  # 2nd positional arg is dtype
            return False
        return _has_float_literal(node.args[0])

    @staticmethod
    def _mixes_with_traced(module, node: ast.Call, bf) -> bool:
        """The scalar participates in arithmetic with a traced operand,
        or is passed straight into a jnp/jax call beside traced args."""
        parent = module.parent(node)
        if isinstance(parent, ast.BinOp):
            other = parent.right if parent.left is node else parent.left
            return bf.value(other).base == TRACED
        if isinstance(parent, ast.Compare):
            for other in (parent.left, *parent.comparators):
                if other is not node and bf.value(other).base == TRACED:
                    return True
            return False
        if isinstance(parent, ast.Call):
            raw = qualified_name(parent.func)
            if raw.split(".", 1)[0] in ("jnp", "jax", "lax"):
                return any(bf.value(a).base == TRACED
                           for a in parent.args if a is not node)
        return False


def _has_float_literal(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
            return True
    return False


class TracedBoolCoercion(CallGraphRule):
    rule_id = "traced-bool-coercion"
    description = ("if/while/assert/and/or/not over a traced value "
                   "inside a jitted program: ConcretizationTypeError at "
                   "best, an implicit device→host sync at worst")

    _HINT = ("use jnp.where / lax.select for value choice, lax.cond / "
             "lax.while_loop for control flow, or hoist the predicate "
             "to the builder if it is static")

    def check_graph(self, graph) -> Iterable[Finding]:
        df = ensure_dataflow(graph)
        seen: set = set()
        for builder, _site, body in _program_sites(graph):
            bf = df.body_facts(body, builder)
            for node, av, kind in bf.tests:
                if av.base != TRACED:
                    continue
                key = (body.module.path, node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    body.module.path, node.lineno, node.col_offset,
                    self.rule_id,
                    f"traced value `{_label(node)}` is coerced to a "
                    f"Python bool by {_TEST_KINDS.get(kind, kind)} "
                    "inside a jitted program",
                    self._HINT,
                    chain=(builder.display, body.display,
                           f"{kind} {_label(node)}"))
