"""Project-wide symbol table + call graph for interprocedural rules.

The per-file rules in this package see one AST at a time; the bug
classes that motivated dtpu-lint v2 live *between* frames: a sync helper
that blocks, two calls below an ``async def``; a device→host readback
three frames under the engine decode-window dispatch; a trace-time
side effect inside a function handed to ``perf.instrumented_jit``. This
module turns the loaded ``Module`` set into one graph so facts can flow
along call edges:

- **Symbol table**: per module, the top-level functions, classes
  (methods, base names, ``self.attr`` types inferred from
  ``self.x = ClassName(...)`` / ``self.x: ClassName``), nested function
  definitions, and the import bindings (``import a.b``,
  ``from a.b import f [as g]``, relative forms).
- **Call edges**: inside each function's own scope, every call is
  recorded as a :class:`CallSite`; the resolver connects ``name(...)``,
  ``self.method(...)``, ``self.attr.method(...)``, ``module.func(...)``
  and ``Class.method(...)`` shapes to project functions. Unresolvable
  calls keep their raw dotted text — the leaf of a finding chain is
  usually exactly such an external name (``np.asarray``).
- **Fact propagation** (cycle-tolerant worklists, each fact set at most
  once per function):

  * *blocking-ness* flows **up** the graph: a sync function blocks when
    its own scope makes a known blocking call or when it calls a sync
    project function that blocks.
  * *hot-path reachability* flows **down** from functions carrying a
    ``# dtpu: hotpath`` anchor comment (on the ``def`` line, or on the
    line directly above the def/first decorator).

Findings built from the graph carry the propagation chain
(``engine._dispatch_window → runner.decode_window → np.asarray``) via
:meth:`CallGraph.hot_chain` / :meth:`CallGraph.blocking_chain`.

Module-name resolution is suffix-based: a loaded file's dotted name is
derived from its path, and ``from dynamo_tpu.engine import perf``
matches any loaded module whose dotted path *ends with*
``dynamo_tpu.engine.perf`` — so the graph works identically on the
installed package (absolute paths) and on test fixture trees.
"""

from __future__ import annotations

import ast
import re

from dynamo_tpu.analysis.core import Module, iter_scope, qualified_name

__all__ = [
    "BLOCKING_CALLS", "CallGraph", "CallSite", "ClassInfo", "FunctionInfo",
    "ModuleInfo", "build_callgraph",
]

_HOTPATH_RE = re.compile(r"#\s*dtpu:\s*hotpath\b")

# Calls that park the calling thread. Exact dotted names; shared with
# rules_async's per-file check and used here as the transitive
# blocking-fact leaves.
BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "os.system": "use `asyncio.create_subprocess_shell` or run in a thread",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or `asyncio.to_thread`",
    "subprocess.call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "socket.create_connection": "use `asyncio.open_connection`",
    "socket.getaddrinfo": "use `loop.getaddrinfo`",
    "socket.gethostbyname": "use `loop.getaddrinfo`",
    "urllib.request.urlopen": "use an async HTTP client or `asyncio.to_thread`",
    "requests.get": "use an async HTTP client or `asyncio.to_thread`",
    "requests.post": "use an async HTTP client or `asyncio.to_thread`",
    "requests.request": "use an async HTTP client or `asyncio.to_thread`",
}


class CallSite:
    """One call expression inside a function's own scope."""

    __slots__ = ("node", "raw", "callee")

    def __init__(self, node: ast.Call, raw: str):
        self.node = node
        self.raw = raw                       # dotted text as written
        self.callee: FunctionInfo | None = None

    @property
    def line(self) -> int:
        return self.node.lineno


class FunctionInfo:
    """One function/method/nested def, plus its graph facts."""

    __slots__ = (
        "qname", "display", "module", "node", "cls", "parent", "calls",
        "nested", "is_async", "is_method", "hot_anchor", "callers",
        "blocking_site", "blocks_through", "is_hot", "hot_via",
    )

    def __init__(self, qname: str, display: str, module: Module,
                 node: ast.FunctionDef | ast.AsyncFunctionDef,
                 cls: "ClassInfo | None" = None,
                 parent: "FunctionInfo | None" = None):
        self.qname = qname
        self.display = display
        self.module = module
        self.node = node
        self.cls = cls
        self.parent = parent
        self.calls: list[CallSite] = []
        self.nested: dict[str, FunctionInfo] = {}
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.is_method = cls is not None and parent is None
        self.hot_anchor = False
        self.callers: list[tuple[FunctionInfo, CallSite]] = []
        # -- propagated facts (each set at most once; cycle-safe) ----------
        self.blocking_site: CallSite | None = None   # direct blocking call
        self.blocks_through: CallSite | None = None  # call to a blocking callee
        self.is_hot = False
        self.hot_via: tuple[FunctionInfo, CallSite] | None = None

    @property
    def blocks(self) -> bool:
        return self.blocking_site is not None or self.blocks_through is not None

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<fn {self.qname}>"


class ClassInfo:
    __slots__ = ("name", "module", "node", "bases", "methods", "attr_types")

    def __init__(self, name: str, module: Module, node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.bases: list[str] = [qualified_name(b) for b in node.bases]
        self.methods: dict[str, FunctionInfo] = {}
        self.attr_types: dict[str, ClassInfo] = {}


class ModuleInfo:
    __slots__ = ("module", "dotted", "functions", "classes", "bindings")

    def __init__(self, module: Module, dotted: str):
        self.module = module
        self.dotted = dotted
        self.functions: dict[str, FunctionInfo] = {}   # top-level defs
        self.classes: dict[str, ClassInfo] = {}
        # name -> ("module", dotted) | ("symbol", module_dotted, symbol)
        self.bindings: dict[str, tuple] = {}


def _path_to_dotted(path: str) -> str:
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return ".".join(seg for seg in p.strip("/").split("/") if seg)


def _has_hot_anchor(module: Module, node) -> bool:
    first = min([d.lineno for d in node.decorator_list] + [node.lineno])
    for ln in (node.lineno, first, first - 1):
        if 1 <= ln <= len(module.lines) and _HOTPATH_RE.search(
                module.lines[ln - 1]):
            return True
    return False


class CallGraph:
    """The built graph: modules, every function by qname, chain helpers."""

    def __init__(self, modules: list[Module]):
        self.modules: list[ModuleInfo] = []
        self.functions: dict[str, FunctionInfo] = {}
        self._by_dotted: dict[str, ModuleInfo] = {}
        self._by_module: dict[int, ModuleInfo] = {}
        self._suffix_cache: dict[str, ModuleInfo | None] = {}
        for m in modules:
            mi = ModuleInfo(m, _path_to_dotted(m.path))
            self.modules.append(mi)
            self._by_dotted[mi.dotted] = mi
            self._by_module[id(m)] = mi
        for mi in self.modules:
            self._collect(mi)
        for mi in self.modules:
            self._collect_bindings(mi)
        for mi in self.modules:
            self._infer_attr_types(mi)
        for fn in self.functions.values():
            self._resolve_calls(fn)
        self._propagate_blocking()
        self._propagate_hot()

    # -- symbol collection ----------------------------------------------------

    def _collect(self, mi: ModuleInfo) -> None:
        short = mi.dotted.rsplit(".", 1)[-1] or mi.dotted
        for node in mi.module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mi, short, node, cls=None, parent=None)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(node.name, mi.module, node)
                mi.classes[node.name] = ci
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._add_function(mi, short, stmt, cls=ci,
                                           parent=None)

    def _add_function(self, mi: ModuleInfo, short: str, node, *,
                      cls: ClassInfo | None,
                      parent: FunctionInfo | None) -> FunctionInfo:
        if parent is not None:
            qname = f"{parent.qname}.<locals>.{node.name}"
        elif cls is not None:
            qname = f"{mi.dotted}:{cls.name}.{node.name}"
        else:
            qname = f"{mi.dotted}:{node.name}"
        display = f"{short}.{node.name}"
        fn = FunctionInfo(qname, display, mi.module, node,
                          cls=cls if parent is None else parent.cls,
                          parent=parent)
        fn.hot_anchor = _has_hot_anchor(mi.module, node)
        self.functions[qname] = fn
        if parent is not None:
            parent.nested[node.name] = fn
        elif cls is not None:
            cls.methods[node.name] = fn
        else:
            mi.functions[node.name] = fn
        # collect own-scope calls and recurse into nested defs
        for sub in iter_scope(node.body):
            if isinstance(sub, ast.Call):
                raw = qualified_name(sub.func)
                if not raw and isinstance(sub.func, ast.Attribute):
                    raw = f"?.{sub.func.attr}"
                fn.calls.append(CallSite(sub, raw))
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mi, short, sub, cls=cls, parent=fn)
        return fn

    def _collect_bindings(self, mi: ModuleInfo) -> None:
        pkg = mi.dotted.rsplit(".", 1)[0] if "." in mi.dotted else ""
        for node in ast.walk(mi.module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        # `import a.b.c as x`: x names the leaf module
                        mi.bindings[alias.asname] = ("module", alias.name)
                    else:
                        # `import a.b.c` binds `a`; later segments resolve
                        # progressively from the bound root.
                        root = alias.name.split(".")[0]
                        mi.bindings[root] = ("module", root)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    segs = mi.dotted.split(".")
                    anchor = segs[: len(segs) - node.level] or segs[:1]
                    base = ".".join(anchor + ([node.module]
                                              if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    sub = f"{base}.{alias.name}" if base else alias.name
                    if self.resolve_module(sub) is not None:
                        mi.bindings[bound] = ("module", sub)
                    else:
                        mi.bindings[bound] = ("symbol", base, alias.name)

    def _infer_attr_types(self, mi: ModuleInfo) -> None:
        for ci in mi.classes.values():
            for fn in ci.methods.values():
                for node in iter_scope(fn.node.body):
                    target = value = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        target, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        target = node.target
                        ann = qualified_name(node.annotation) \
                            if node.annotation is not None else ""
                        hit = self._resolve_class(mi, ann)
                        if hit is not None and _is_self_attr(target):
                            ci.attr_types.setdefault(target.attr, hit)
                        value = node.value
                    if (target is None or value is None
                            or not _is_self_attr(target)):
                        continue
                    if isinstance(value, ast.Call):
                        hit = self._resolve_class(mi, qualified_name(value.func))
                        if hit is not None:
                            ci.attr_types.setdefault(target.attr, hit)

    def _resolve_class(self, mi: ModuleInfo, dotted: str) -> ClassInfo | None:
        if not dotted:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            if parts[0] in mi.classes:
                return mi.classes[parts[0]]
            b = mi.bindings.get(parts[0])
            if b is not None and b[0] == "symbol":
                target = self.resolve_module(b[1])
                if target is not None:
                    return target.classes.get(b[2])
            return None
        b = mi.bindings.get(parts[0])
        if b is not None and b[0] == "module":
            target = self._resolve_dotted_module(b[1], parts[1:-1])
            if target is not None:
                return target.classes.get(parts[-1])
        return None

    # -- module resolution ----------------------------------------------------

    def resolve_module(self, dotted: str) -> ModuleInfo | None:
        """Exact dotted-name match, else unique-suffix match."""
        if dotted in self._by_dotted:
            return self._by_dotted[dotted]
        if dotted in self._suffix_cache:
            return self._suffix_cache[dotted]
        tail = "." + dotted
        hits = [mi for name, mi in self._by_dotted.items()
                if name.endswith(tail)]
        out = hits[0] if len(hits) == 1 else None
        self._suffix_cache[dotted] = out
        return out

    def _resolve_dotted_module(self, root: str,
                               middle: list[str]) -> ModuleInfo | None:
        """Longest prefix of root.middle... that names a loaded module."""
        for cut in range(len(middle), -1, -1):
            mi = self.resolve_module(".".join([root] + middle[:cut]))
            if mi is not None:
                return mi
        return None

    # -- call resolution ------------------------------------------------------

    def _resolve_calls(self, fn: FunctionInfo) -> None:
        mi = self._by_module[id(fn.module)]
        for site in fn.calls:
            callee = self._resolve_call(mi, fn, site.raw)
            if callee is not None:
                site.callee = callee
                callee.callers.append((fn, site))

    def _resolve_call(self, mi: ModuleInfo, fn: FunctionInfo,
                      raw: str) -> FunctionInfo | None:
        if not raw or raw.startswith("?."):
            return None
        parts = raw.split(".")
        head = parts[0]
        if head in ("self", "cls") and fn.cls is not None:
            if len(parts) == 2:
                return self._method_lookup(mi, fn.cls, parts[1])
            if len(parts) == 3:
                attr_cls = fn.cls.attr_types.get(parts[1])
                if attr_cls is not None:
                    owner = self._by_module.get(id(attr_cls.module), mi)
                    return self._method_lookup(owner, attr_cls, parts[2])
            return None
        if len(parts) == 1:
            # nested def in this or an enclosing function, else module fn
            scope: FunctionInfo | None = fn
            while scope is not None:
                if head in scope.nested:
                    return scope.nested[head]
                scope = scope.parent
            hit = mi.functions.get(head)
            if hit is not None:
                return hit
            if head in mi.classes:   # ClassName(...) -> __init__
                return mi.classes[head].methods.get("__init__")
            b = mi.bindings.get(head)
            if b is not None and b[0] == "symbol":
                target = self.resolve_module(b[1])
                if target is not None:
                    if b[2] in target.functions:
                        return target.functions[b[2]]
                    if b[2] in target.classes:
                        return target.classes[b[2]].methods.get("__init__")
            return None
        # dotted: ClassName.method in this module, else via import binding
        if head in mi.classes and len(parts) == 2:
            return self._method_lookup(mi, mi.classes[head], parts[1])
        b = mi.bindings.get(head)
        if b is None:
            return None
        if b[0] == "symbol":
            target = self.resolve_module(b[1])
            if target is not None and b[2] in target.classes \
                    and len(parts) == 2:
                return self._method_lookup(target, target.classes[b[2]],
                                           parts[1])
            return None
        target = self._resolve_dotted_module(b[1], parts[1:-1])
        if target is None:
            return None
        leaf = parts[-1]
        if leaf in target.functions:
            return target.functions[leaf]
        if leaf in target.classes:
            return target.classes[leaf].methods.get("__init__")
        if len(parts) >= 3 and parts[-2] in target.classes:
            return self._method_lookup(target, target.classes[parts[-2]], leaf)
        return None

    def _method_lookup(self, mi: ModuleInfo, cls: ClassInfo,
                       name: str) -> FunctionInfo | None:
        seen: set[int] = set()
        stack = [(mi, cls)]
        while stack:
            owner_mi, ci = stack.pop()
            if id(ci) in seen:
                continue
            seen.add(id(ci))
            if name in ci.methods:
                return ci.methods[name]
            for base in ci.bases:
                base_ci = self._resolve_class(owner_mi, base)
                if base_ci is not None:
                    base_mi = self._by_module.get(id(base_ci.module),
                                                  owner_mi)
                    stack.append((base_mi, base_ci))
        return None

    # -- fact propagation -----------------------------------------------------

    def _propagate_blocking(self) -> None:
        worklist: list[FunctionInfo] = []
        for fn in self.functions.values():
            for site in fn.calls:
                if site.raw in BLOCKING_CALLS or site.raw == "open" or (
                        isinstance(site.node.func, ast.Attribute)
                        and site.node.func.attr == "block_until_ready"):
                    if fn.module.is_suppressed(site.line,
                                               "blocking-call-in-async"):
                        # A suppression ON the blocking line of a sync
                        # helper declares the helper allowed-to-block
                        # (startup/cold I/O): it stops propagation, so
                        # one source-side rationale covers every caller.
                        continue
                    fn.blocking_site = site
                    break
            if fn.blocking_site is not None and not fn.is_async:
                worklist.append(fn)
        while worklist:
            fn = worklist.pop()
            for caller, site in fn.callers:
                if caller.is_async or caller.blocks:
                    continue
                caller.blocks_through = site
                worklist.append(caller)

    def _propagate_hot(self) -> None:
        worklist = [fn for fn in self.functions.values() if fn.hot_anchor]
        for fn in worklist:
            fn.is_hot = True
        while worklist:
            fn = worklist.pop()
            for site in fn.calls:
                c = site.callee
                if c is not None and not c.is_hot:
                    c.is_hot = True
                    c.hot_via = (fn, site)
                    worklist.append(c)

    # -- chain helpers --------------------------------------------------------

    def hot_chain(self, fn: FunctionInfo) -> list[str]:
        """Display names from the hot-path anchor down to ``fn``."""
        parts = [fn.display]
        cur, seen = fn, {fn.qname}
        while cur.hot_via is not None:
            cur = cur.hot_via[0]
            if cur.qname in seen:
                break
            seen.add(cur.qname)
            parts.append(cur.display)
        return list(reversed(parts))

    def blocking_chain(self, fn: FunctionInfo) -> list[str]:
        """Display names from ``fn`` down to the concrete blocking leaf."""
        parts = [fn.display]
        cur, seen = fn, {fn.qname}
        while cur.blocks_through is not None:
            nxt = cur.blocks_through.callee
            if nxt is None or nxt.qname in seen:
                break
            seen.add(nxt.qname)
            parts.append(nxt.display)
            cur = nxt
        if cur.blocking_site is not None:
            parts.append(cur.blocking_site.raw)
        return parts

    # -- stats (CLI / check.sh) -----------------------------------------------

    def stats(self) -> dict:
        edges = sum(1 for fn in self.functions.values()
                    for s in fn.calls if s.callee is not None)
        return {"modules": len(self.modules),
                "functions": len(self.functions),
                "edges": edges,
                "hot": sum(f.is_hot for f in self.functions.values()),
                "blocking": sum(f.blocks for f in self.functions.values())}


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def build_callgraph(modules: list[Module]) -> CallGraph:
    return CallGraph(modules)
