"""impure-jit-program: functions handed to perf.instrumented_jit must
be trace-pure.

``jax.jit`` runs the Python body ONCE per compile and replays the traced
graph after — any side effect in the program function (or anything it
calls, or any nested def it traces inline) executes at trace time only:

- ``time.*`` / ``random.*`` reads bake a single stale value into the
  compiled program — the PR 9 compile-observatory double-compile bugs
  were exactly trace-time state leaking into program identity;
- logging / metrics / ``print`` fire once per compile, silently skewing
  the observatory's counters and confusing "why did this log line stop";
- mutating ``self`` or closure state (``global``/``nonlocal``) from
  inside a traced body runs once, not per call — a correctness trap.

The rule resolves the function argument of every
``perf.instrumented_jit(program, fn, ...)`` call site through the call
graph (nested defs included — the repo's jitted programs are almost all
``def step(...)`` closures) and walks it plus its transitive project
callees and nested defs. Findings land at the ``instrumented_jit`` call
site with the chain to the impure leaf.

``jax.random``/``jnp`` are of course fine; only host-side ``random.*``
is impure. Metric mutation is matched on metric-shaped receivers
(``m_*``, ``*metric*``, ``*counter*``, ``*gauge*``, ``*hist*``) so
in-graph ``.at[...].set(...)`` updates never false-positive.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import CallGraphRule, Finding, iter_scope

_IMPURE_PREFIXES = ("time.", "random.", "logging.")
_LOGGER_ROOTS = {"log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical"}
_METRIC_METHODS = {"inc", "observe", "set", "labels"}
_METRIC_HINTS = ("metric", "counter", "gauge", "hist")


def _metric_receiver(recv: str) -> bool:
    leaf = recv.rsplit(".", 1)[-1].lower()
    return leaf.startswith("m_") or any(h in leaf for h in _METRIC_HINTS)


def _impure_call_label(site) -> str | None:
    raw = site.raw
    if any(raw.startswith(p) for p in _IMPURE_PREFIXES):
        return raw
    if raw == "print":
        return "print"
    parts = raw.split(".")
    if len(parts) >= 2:
        root, leaf = parts[0], parts[-1]
        if root in _LOGGER_ROOTS and leaf in _LOG_METHODS:
            return raw
        if leaf in _LOG_METHODS and parts[-2] in _LOGGER_ROOTS:
            return raw
        if leaf in _METRIC_METHODS and _metric_receiver(
                ".".join(parts[:-1])):
            return raw
    return None


def _impure_stmt_label(fn) -> tuple[ast.AST, str] | None:
    """self-/closure-state mutation inside the function's own scope."""
    for node in iter_scope(fn.node.body):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            return node, f"{kind} {', '.join(node.names)}"
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                return node, f"self.{t.attr} = ..."
    return None


class ImpureJitProgram(CallGraphRule):
    rule_id = "impure-jit-program"
    description = ("function passed to perf.instrumented_jit (transitively) "
                   "calls time/random/logging/metrics or mutates "
                   "self/closure state: trace-time side effects run once "
                   "per COMPILE, baking stale values into the program and "
                   "skewing the compile observatory")

    def check_graph(self, graph) -> Iterable[Finding]:
        for caller in graph.functions.values():
            for site in caller.calls:
                if not site.raw.endswith("instrumented_jit") \
                        or len(site.node.args) < 2:
                    continue
                arg = site.node.args[1]
                if not isinstance(arg, ast.Name):
                    continue
                target = self._resolve_local(graph, caller, arg.id)
                if target is None:
                    continue
                hit = self._find_impurity(graph, target)
                if hit is None:
                    continue
                leaf_label, chain = hit
                yield Finding(
                    caller.module.path, site.node.lineno,
                    site.node.col_offset, self.rule_id,
                    f"program `{arg.id}` passed to instrumented_jit is "
                    f"impure: `{leaf_label}` runs once per compile, not "
                    "per call",
                    "hoist the side effect out of the traced body (record "
                    "around the dispatch, not inside the program), or "
                    "suppress with why trace-time execution is intended",
                    chain=chain)

    @staticmethod
    def _resolve_local(graph, caller, name: str):
        """The program argument: a nested def in the calling function (the
        repo idiom), an enclosing function's nested def, or a module-level
        function of the same module."""
        scope = caller
        while scope is not None:
            if name in scope.nested:
                return scope.nested[name]
            scope = scope.parent
        for mi in graph.modules:
            if mi.module is caller.module:
                return mi.functions.get(name)
        return None

    @staticmethod
    def _find_impurity(graph, target):
        """BFS over target + nested defs + resolved project callees;
        returns (leaf_label, chain) for the first impurity found."""
        queue = [(target, (target.display,))]
        seen = {target.qname}
        while queue:
            fn, path = queue.pop(0)
            stmt_hit = _impure_stmt_label(fn)
            if stmt_hit is not None:
                return stmt_hit[1], (*path, stmt_hit[1])
            for site in fn.calls:
                label = _impure_call_label(site)
                if label is not None:
                    return label, (*path, label)
            for nxt in (*fn.nested.values(),
                        *(s.callee for s in fn.calls
                          if s.callee is not None)):
                if nxt.qname not in seen:
                    seen.add(nxt.qname)
                    queue.append((nxt, (*path, nxt.display)))
        return None
