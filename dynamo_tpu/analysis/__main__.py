"""CLI: ``python -m dynamo_tpu.analysis [paths] [options]``.

Exit codes: 0 clean, 1 findings / unparseable files / budget violations,
2 usage error. With no paths, analyzes the installed dynamo_tpu package —
so the bare module invocation is the repo gate scripts/check.sh runs.

Options beyond path selection:

- ``--format json``: versioned, schema-pinned machine output (findings
  sorted by (path, line, col, rule), suppression counts, graph stats) —
  stable across runs so lint gates can diff them. ``--json`` stays as
  the legacy bare-findings-array alias.
- ``--format sarif``: SARIF 2.1.0 (byte-stable, sorted like json) for
  CI/code-review inline annotation; ``--sarif-out FILE`` writes the
  SARIF artifact alongside any primary format (check.sh uses it to get
  the human gate output AND the artifact from one pass).
- ``--no-cache``: bypass the content-hash run cache. The CLI caches
  under ``.dtpu-lint-cache/`` by default (warm unchanged-repo runs are
  sub-second); the cache key covers file contents, the analyzer's own
  sources, the rule selection, and today's date (suppression expiry).
- ``--budget FILE``: the suppression ratchet. FILE maps rule id ->
  maximum allowed suppression directives; any rule over its
  budget fails the run. Ratchet down by lowering the number in the
  committed file when suppressions get fixed; never raise a number
  without review (docs/ANALYSIS.md "Suppression ratchet").
- ``--callgraph MODULE``: debug dump of one module's functions, facts
  (async/hot/blocks) and resolved edges.
- ``--stats``: one summary line (modules/functions/edges/rules) on
  stderr — check.sh prints it so gate logs record graph size drift.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from dynamo_tpu.analysis import default_rules, run_analysis
from dynamo_tpu.analysis.cache import DEFAULT_CACHE_DIR
from dynamo_tpu.analysis.sarif import render_sarif

SCHEMA_VERSION = 1


def _dump_callgraph(run, want: str) -> int:
    """Sorted, deterministic dump of one module's slice of the graph."""
    if run.graph is None:
        print("error: call graph not built (narrow --select?)",
              file=sys.stderr)
        return 2
    hits = [mi for mi in run.graph.modules
            if mi.dotted == want or mi.dotted.endswith("." + want)
            or mi.module.path == want]
    if not hits:
        print(f"error: no loaded module matches `{want}`", file=sys.stderr)
        return 2
    for mi in sorted(hits, key=lambda m: m.dotted):
        fns = sorted((fn for fn in run.graph.functions.values()
                      if fn.module is mi.module),
                     key=lambda f: f.node.lineno)
        print(f"{mi.module.path} ({mi.dotted}): {len(fns)} function(s)")
        for fn in fns:
            facts = [k for k, on in (("async", fn.is_async),
                                     ("hotpath-anchor", fn.hot_anchor),
                                     ("hot", fn.is_hot),
                                     ("blocks", fn.blocks)) if on]
            suffix = f"  [{', '.join(facts)}]" if facts else ""
            print(f"  {fn.qname}:{fn.node.lineno}{suffix}")
            for site in fn.calls:
                if site.callee is not None:
                    print(f"    -> {site.callee.qname}  ({site.raw}, "
                          f"line {site.line})")
    return 0


def _check_budget(run, budget_path: str) -> list[str]:
    try:
        budget = json.loads(Path(budget_path).read_text(encoding="utf-8"))
    except (OSError, ValueError) as exc:
        return [f"budget file unreadable: {exc}"]
    counts = run.suppression_counts()
    errors = []
    for rule_id in sorted(counts):
        allowed = budget.get(rule_id, 0)
        if counts[rule_id] > allowed:
            errors.append(
                f"suppression budget exceeded for [{rule_id}]: "
                f"{counts[rule_id]} > {allowed} — fix the new finding "
                f"instead of suppressing it, or (with review) raise "
                f"{budget_path}")
    return errors


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dtpu-lint: interprocedural async/JAX/wire hazard "
                    "analyzer")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the "
                             "dynamo_tpu package)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", dest="fmt",
                        help="output format (json is versioned and "
                             "schema-pinned for gate diffing; sarif is "
                             "SARIF 2.1.0 for CI annotation — both "
                             "byte-stable)")
    parser.add_argument("--sarif-out", metavar="FILE",
                        help="also write the SARIF 2.1.0 artifact to "
                             "FILE (any --format)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the .dtpu-lint-cache content-hash "
                             "run cache")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="legacy alias: emit findings as a bare JSON "
                             "array")
    parser.add_argument("--select", metavar="IDS",
                        help="comma-separated rule ids to run")
    parser.add_argument("--budget", metavar="FILE",
                        help="suppression-ratchet budget file "
                             "(deploy/lint-budget.json); any rule over "
                             "its count fails")
    parser.add_argument("--callgraph", metavar="MODULE",
                        help="dump one module's call-graph slice "
                             "(dotted suffix or file path) and exit")
    parser.add_argument("--stats", action="store_true",
                        help="print modules/functions/edges/rules summary "
                             "on stderr")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.rule_id}\n    {rule.description}")
        return 0

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    # --callgraph needs the live graph, which a cache hit skips building
    cache_dir = None if (args.no_cache or args.callgraph) \
        else DEFAULT_CACHE_DIR
    try:
        run = run_analysis(paths, select, cache_dir=cache_dir)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.callgraph:
        return _dump_callgraph(run, args.callgraph)

    budget_errors = _check_budget(run, args.budget) if args.budget else []
    stats = run.graph_stats()
    stats["rules"] = len(run.rules)
    stats["findings"] = len(run.findings)

    if args.stats:
        # `cached` rides the stderr line only: the json/sarif documents
        # must stay byte-identical between cold and warm runs
        extra = {"cached": 1} if run.cached else {}
        print("dtpu-lint: " + " ".join(
            f"{k}={v}" for k, v in sorted({**stats, **extra}.items())),
            file=sys.stderr)

    findings = run.findings
    if args.sarif_out:
        Path(args.sarif_out).write_text(
            render_sarif(findings, default_rules()) + "\n",
            encoding="utf-8")
    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    elif args.fmt == "sarif":
        print(render_sarif(findings, default_rules()))
    elif args.fmt == "json":
        doc = {
            "version": SCHEMA_VERSION,
            "findings": [f.to_json() for f in findings],
            "suppressions": run.suppression_counts(),
            "stats": dict(sorted(stats.items())),
            "budget_errors": budget_errors,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s). Fix, or suppress with "
                  "`# dtpu: ignore[rule-id]  -- rationale` "
                  "(see docs/ANALYSIS.md).", file=sys.stderr)
        for err in budget_errors:
            print(f"budget: {err}", file=sys.stderr)
    return 1 if (findings or budget_errors) else 0


if __name__ == "__main__":
    sys.exit(main())
