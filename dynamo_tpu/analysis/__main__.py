"""CLI: ``python -m dynamo_tpu.analysis [paths] [--json] [--select ids]``.

Exit codes: 0 clean, 1 findings (or unparseable files), 2 usage error.
With no paths, analyzes the installed dynamo_tpu package — so the bare
module invocation is the repo gate scripts/check.sh runs.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from dynamo_tpu.analysis import analyze_paths, default_rules


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dtpu-lint: async/JAX/wire hazard analyzer")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the "
                             "dynamo_tpu package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array")
    parser.add_argument("--select", metavar="IDS",
                        help="comma-separated rule ids to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.rule_id}\n    {rule.description}")
        return 0

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    try:
        findings = analyze_paths(paths, select)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s). Fix, or suppress with "
                  "`# dtpu: ignore[rule-id]  -- rationale` "
                  "(see docs/ANALYSIS.md).", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
