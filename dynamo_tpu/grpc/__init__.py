"""KServe v2 gRPC frontend (reference lib/llm/src/grpc/service/kserve.rs).

``kserve_pb2.py`` is generated from ``kserve.proto`` and committed;
regenerate with ``protoc --python_out=dynamo_tpu/grpc
--proto_path=dynamo_tpu/grpc dynamo_tpu/grpc/kserve.proto``.
"""
