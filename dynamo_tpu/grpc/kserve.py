"""KServe v2 gRPC inference service.

Capability parity with the reference KServe frontend
(lib/llm/src/grpc/service/kserve.rs:85): liveness/readiness probes, model
readiness/metadata from the model manager, and text generation over
ModelInfer (unary) / ModelStreamInfer (server streaming): a BYTES
"text_input" tensor in, "text_output" tensors out, generation parameters
(max_tokens, temperature, top_p, streaming) via request parameters.

grpc_tools isn't available in the image, so the service is registered
through grpc.aio generic method handlers with the protoc-generated
message classes — same wire format, no codegen'd stubs needed.
"""

from __future__ import annotations

import grpc

from dynamo_tpu.grpc import kserve_pb2 as pb
from dynamo_tpu.llm.preprocessor import aggregate_chat_stream
from dynamo_tpu.llm.protocols import ChatCompletionRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kserve")

SERVICE = "inference.GRPCInferenceService"


def _param(p: pb.InferParameter):
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


def _text_input(request: pb.ModelInferRequest) -> str:
    for t in request.inputs:
        if t.name == "text_input" and t.contents.bytes_contents:
            return t.contents.bytes_contents[0].decode("utf-8", "replace")
    raise ValueError("request has no 'text_input' BYTES tensor")


def _chat_request(model: str, request: pb.ModelInferRequest,
                  stream: bool) -> ChatCompletionRequest:
    params = {k: _param(v) for k, v in request.parameters.items()}
    return ChatCompletionRequest(
        model=model,
        messages=[{"role": "user", "content": _text_input(request)}],
        max_tokens=int(params.get("max_tokens") or 64),
        temperature=params.get("temperature"),
        top_p=params.get("top_p"),
        stream=stream,
        stream_options={"include_usage": True})


def _text_response(model: str, rid: str, text: str,
                   finish: str | None = None) -> pb.ModelInferResponse:
    resp = pb.ModelInferResponse(model_name=model, id=rid)
    out = resp.outputs.add()
    out.name = "text_output"
    out.datatype = "BYTES"
    out.shape.append(1)
    out.contents.bytes_contents.append(text.encode())
    if finish:
        resp.parameters["finish_reason"].string_param = finish
    return resp


class KServeService:
    def __init__(self, manager):
        self.manager = manager

    # -- probes ---------------------------------------------------------------
    async def server_live(self, request, context):
        return pb.ServerLiveResponse(live=True)

    async def server_ready(self, request, context):
        return pb.ServerReadyResponse(ready=True)

    async def model_ready(self, request, context):
        return pb.ModelReadyResponse(
            ready=self.manager.get(request.name) is not None)

    async def model_metadata(self, request, context):
        served = self.manager.get(request.name)
        if served is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"model {request.name!r} not found")
        meta = pb.ModelMetadataResponse(name=request.name,
                                        platform="dynamo-tpu")
        inp = meta.inputs.add()
        inp.name, inp.datatype = "text_input", "BYTES"
        inp.shape.append(1)
        out = meta.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.append(1)
        return meta

    # -- inference ------------------------------------------------------------
    async def model_infer(self, request, context):
        served = self.manager.get(request.model_name)
        if served is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"model {request.model_name!r} not found")
        try:
            chat_req = _chat_request(request.model_name, request, stream=False)
        except ValueError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        ctx = Context()
        chunks = served.preprocessor.generate(chat_req, ctx)
        full = await aggregate_chat_stream(chunks, 0)
        msg = full["choices"][0]["message"]
        return _text_response(request.model_name, request.id,
                              msg.get("content") or "",
                              full["choices"][0].get("finish_reason"))

    async def model_stream_infer(self, request_iterator, context):
        async for request in request_iterator:
            served = self.manager.get(request.model_name)
            if served is None:
                yield pb.ModelStreamInferResponse(
                    error_message=f"model {request.model_name!r} not found")
                continue
            try:
                chat_req = _chat_request(request.model_name, request,
                                         stream=True)
            except ValueError as exc:
                yield pb.ModelStreamInferResponse(error_message=str(exc))
                continue
            ctx = Context()
            try:
                async for chunk in served.preprocessor.generate(chat_req,
                                                                ctx):
                    for choice in chunk.get("choices", []):
                        piece = choice.get("delta", {}).get("content")
                        finish = choice.get("finish_reason")
                        if piece or finish:
                            yield pb.ModelStreamInferResponse(
                                infer_response=_text_response(
                                    request.model_name, request.id,
                                    piece or "", finish))
            except Exception as exc:  # noqa: BLE001 — ship to caller
                log.exception("stream infer failed")
                yield pb.ModelStreamInferResponse(
                    error_message=f"{type(exc).__name__}: {exc}")


def make_server(manager, host: str = "0.0.0.0",
                port: int = 0) -> tuple[grpc.aio.Server, int]:
    """Build (not yet started) grpc.aio server with the KServe service
    registered via generic handlers."""
    svc = KServeService(manager)
    rpcs = {
        "ServerLive": grpc.unary_unary_rpc_method_handler(
            svc.server_live,
            request_deserializer=pb.ServerLiveRequest.FromString,
            response_serializer=pb.ServerLiveResponse.SerializeToString),
        "ServerReady": grpc.unary_unary_rpc_method_handler(
            svc.server_ready,
            request_deserializer=pb.ServerReadyRequest.FromString,
            response_serializer=pb.ServerReadyResponse.SerializeToString),
        "ModelReady": grpc.unary_unary_rpc_method_handler(
            svc.model_ready,
            request_deserializer=pb.ModelReadyRequest.FromString,
            response_serializer=pb.ModelReadyResponse.SerializeToString),
        "ModelMetadata": grpc.unary_unary_rpc_method_handler(
            svc.model_metadata,
            request_deserializer=pb.ModelMetadataRequest.FromString,
            response_serializer=pb.ModelMetadataResponse.SerializeToString),
        "ModelInfer": grpc.unary_unary_rpc_method_handler(
            svc.model_infer,
            request_deserializer=pb.ModelInferRequest.FromString,
            response_serializer=pb.ModelInferResponse.SerializeToString),
        "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
            svc.model_stream_infer,
            request_deserializer=pb.ModelInferRequest.FromString,
            response_serializer=(
                pb.ModelStreamInferResponse.SerializeToString)),
    }
    server = grpc.aio.server()
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, rpcs),))
    bound = server.add_insecure_port(f"{host}:{port}")
    return server, bound
