"""ctypes wrapper for the C++ radix index (radix_tree.cpp): same interface
as the pure-Python RadixTree in llm/kv_router/indexer.py."""

from __future__ import annotations

import ctypes
from typing import Iterable

from dynamo_tpu.native import load_library

_lib = load_library("radix_tree")

if _lib is not None:
    _u64p = ctypes.POINTER(ctypes.c_uint64)
    _u32p = ctypes.POINTER(ctypes.c_uint32)
    _lib.radix_new.restype = ctypes.c_void_p
    _lib.radix_free.argtypes = [ctypes.c_void_p]
    _lib.radix_stored.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u64p,
                                  ctypes.c_size_t]
    _lib.radix_removed.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u64p,
                                   ctypes.c_size_t]
    _lib.radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    _lib.radix_bump_events.argtypes = [ctypes.c_void_p]
    _lib.radix_event_count.argtypes = [ctypes.c_void_p]
    _lib.radix_event_count.restype = ctypes.c_uint64
    _lib.radix_num_blocks.argtypes = [ctypes.c_void_p]
    _lib.radix_num_blocks.restype = ctypes.c_size_t
    _lib.radix_find_matches.argtypes = [ctypes.c_void_p, _u64p,
                                        ctypes.c_size_t, _u64p, _u32p,
                                        ctypes.c_size_t]
    _lib.radix_find_matches.restype = ctypes.c_size_t
    _lib.radix_workers.argtypes = [ctypes.c_void_p, _u64p, ctypes.c_size_t]
    _lib.radix_workers.restype = ctypes.c_size_t
    _lib.radix_worker_block_count.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64]
    _lib.radix_worker_block_count.restype = ctypes.c_size_t
    _lib.radix_worker_blocks.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         _u64p, ctypes.c_size_t]
    _lib.radix_worker_blocks.restype = ctypes.c_size_t

available = _lib is not None

_MASK = 2**64 - 1


def _arr(hashes: list[int]):
    n = len(hashes)
    return (ctypes.c_uint64 * n)(*[h & _MASK for h in hashes]), n


class NativeRadixTree:
    """Drop-in for llm.kv_router.indexer.RadixTree backed by the C++ core.
    Hash values are canonicalized to unsigned 64-bit (the Python tree
    stores xxh3 ints, already unsigned)."""

    MAX_WORKERS = 4096

    def __init__(self):
        assert _lib is not None
        self._p = ctypes.c_void_p(_lib.radix_new())

    def __del__(self):
        p = getattr(self, "_p", None)
        if p and _lib is not None:
            _lib.radix_free(p)
            self._p = None

    @property
    def event_count(self) -> int:
        return _lib.radix_event_count(self._p)

    def apply_event(self, event) -> None:
        worker = event.worker_id & _MASK
        ev = event.event
        if ev.kind == "stored":
            arr, n = _arr(list(ev.block_hashes))
            _lib.radix_stored(self._p, worker, arr, n)
        elif ev.kind == "removed":
            arr, n = _arr(list(ev.block_hashes))
            _lib.radix_removed(self._p, worker, arr, n)
        elif ev.kind == "cleared":
            _lib.radix_remove_worker(self._p, worker)
            _lib.radix_bump_events(self._p)

    def remove_worker(self, worker_id: int) -> None:
        _lib.radix_remove_worker(self._p, worker_id & _MASK)

    def find_matches(self, block_hashes: Iterable[int]) -> dict[int, int]:
        hashes = list(block_hashes)
        arr, n = _arr(hashes)
        cap = self.MAX_WORKERS
        workers = (ctypes.c_uint64 * cap)()
        scores = (ctypes.c_uint32 * cap)()
        m = _lib.radix_find_matches(self._p, arr, n, workers, scores, cap)
        return {int(workers[i]): int(scores[i]) for i in range(m)}

    def workers(self) -> set[int]:
        cap = self.MAX_WORKERS
        out = (ctypes.c_uint64 * cap)()
        m = _lib.radix_workers(self._p, out, cap)
        return {int(out[i]) for i in range(m)}

    @property
    def num_blocks(self) -> int:
        return _lib.radix_num_blocks(self._p)

    def dump_as_events(self) -> list:
        from dynamo_tpu.llm.kv_router.protocols import (KvCacheEvent,
                                                        RouterEvent)
        out = []
        for w in sorted(self.workers()):
            cnt = _lib.radix_worker_block_count(self._p, w)
            buf = (ctypes.c_uint64 * cnt)()
            m = _lib.radix_worker_blocks(self._p, w, buf, cnt)
            hashes = sorted(int(buf[i]) for i in range(m))
            if hashes:
                out.append(RouterEvent(worker_id=w,
                                       event=KvCacheEvent.stored(hashes)))
        return out
