// Native radix index of cached KV blocks per worker — the C++ core of the
// KV-cache-aware router (the role the reference implements in Rust,
// lib/llm/src/kv_router/indexer.rs RadixTree). Because block hashes chain
// their whole prefix, the radix structure is implicit in the hashes: the
// index maps block_hash -> holders and longest-prefix matching narrows the
// holder set walking the request's hashes in order.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image). Build:
//   g++ -O2 -shared -fPIC -std=c++17 radix_tree.cpp -o _radix.so

#include <cstdint>
#include <cstddef>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct RadixIndex {
    std::unordered_map<uint64_t, std::unordered_set<uint64_t>> blocks;
    std::unordered_map<uint64_t, std::unordered_set<uint64_t>> by_worker;
    uint64_t event_count = 0;
};

}  // namespace

extern "C" {

void* radix_new() { return new RadixIndex(); }

void radix_free(void* p) { delete static_cast<RadixIndex*>(p); }

void radix_stored(void* p, uint64_t worker, const uint64_t* hashes,
                  size_t n) {
    auto* idx = static_cast<RadixIndex*>(p);
    idx->event_count++;
    auto& mine = idx->by_worker[worker];
    for (size_t i = 0; i < n; i++) {
        idx->blocks[hashes[i]].insert(worker);
        mine.insert(hashes[i]);
    }
}

void radix_removed(void* p, uint64_t worker, const uint64_t* hashes,
                   size_t n) {
    auto* idx = static_cast<RadixIndex*>(p);
    idx->event_count++;
    auto by = idx->by_worker.find(worker);
    for (size_t i = 0; i < n; i++) {
        auto it = idx->blocks.find(hashes[i]);
        if (it != idx->blocks.end()) {
            it->second.erase(worker);
            if (it->second.empty()) idx->blocks.erase(it);
        }
        if (by != idx->by_worker.end()) by->second.erase(hashes[i]);
    }
}

void radix_remove_worker(void* p, uint64_t worker) {
    auto* idx = static_cast<RadixIndex*>(p);
    auto by = idx->by_worker.find(worker);
    if (by == idx->by_worker.end()) return;
    for (uint64_t h : by->second) {
        auto it = idx->blocks.find(h);
        if (it != idx->blocks.end()) {
            it->second.erase(worker);
            if (it->second.empty()) idx->blocks.erase(it);
        }
    }
    idx->by_worker.erase(by);
}

void radix_bump_events(void* p) {
    static_cast<RadixIndex*>(p)->event_count++;
}

uint64_t radix_event_count(void* p) {
    return static_cast<RadixIndex*>(p)->event_count;
}

size_t radix_num_blocks(void* p) {
    return static_cast<RadixIndex*>(p)->blocks.size();
}

// Longest-prefix overlap per worker: a worker scores i+1 only if it holds
// blocks 0..i contiguously. Writes up to cap (worker, score) pairs;
// returns the pair count.
size_t radix_find_matches(void* p, const uint64_t* hashes, size_t n,
                          uint64_t* workers_out, uint32_t* scores_out,
                          size_t cap) {
    auto* idx = static_cast<RadixIndex*>(p);
    std::unordered_map<uint64_t, uint32_t> scores;
    std::vector<uint64_t> active;
    bool first = true;
    for (size_t i = 0; i < n; i++) {
        auto it = idx->blocks.find(hashes[i]);
        if (it == idx->blocks.end() || it->second.empty()) break;
        if (first) {
            active.assign(it->second.begin(), it->second.end());
            first = false;
        } else {
            std::vector<uint64_t> next;
            next.reserve(active.size());
            for (uint64_t w : active)
                if (it->second.count(w)) next.push_back(w);
            active.swap(next);
        }
        if (active.empty()) break;
        for (uint64_t w : active) scores[w]++;
    }
    size_t out = 0;
    for (auto& kv : scores) {
        if (out >= cap) break;
        workers_out[out] = kv.first;
        scores_out[out] = kv.second;
        out++;
    }
    return out;
}

size_t radix_num_workers(void* p) {
    auto* idx = static_cast<RadixIndex*>(p);
    size_t n = 0;
    for (auto& kv : idx->by_worker)
        if (!kv.second.empty()) n++;
    return n;
}

// Enumerate workers with blocks; writes up to cap ids, returns count.
size_t radix_workers(void* p, uint64_t* out, size_t cap) {
    auto* idx = static_cast<RadixIndex*>(p);
    size_t n = 0;
    for (auto& kv : idx->by_worker) {
        if (kv.second.empty()) continue;
        if (n >= cap) break;
        out[n++] = kv.first;
    }
    return n;
}

size_t radix_worker_block_count(void* p, uint64_t worker) {
    auto* idx = static_cast<RadixIndex*>(p);
    auto it = idx->by_worker.find(worker);
    return it == idx->by_worker.end() ? 0 : it->second.size();
}

size_t radix_worker_blocks(void* p, uint64_t worker, uint64_t* out,
                           size_t cap) {
    auto* idx = static_cast<RadixIndex*>(p);
    auto it = idx->by_worker.find(worker);
    if (it == idx->by_worker.end()) return 0;
    size_t n = 0;
    for (uint64_t h : it->second) {
        if (n >= cap) break;
        out[n++] = h;
    }
    return n;
}

}  // extern "C"
