"""Native (C++) runtime components with build-on-first-use + ctypes.

The reference implements its runtime hot paths in Rust/C++; here the
compute path is JAX/XLA and the host-side hot structures get C++ cores
(radix_tree.cpp so far). No pybind11 in the image, so bindings are plain
ctypes over a C ABI; the shared object compiles from source on first use
(g++ is baked into the image) and callers fall back to the pure-Python
implementation if compilation fails or DTPU_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("native")

_DIR = os.path.dirname(os.path.abspath(__file__))


def load_library(name: str) -> ctypes.CDLL | None:
    """Load (building if needed) lib ``name`` (e.g. "radix_tree" ->
    _radix_tree.so). Returns None when native is disabled or the build
    fails."""
    if os.environ.get("DTPU_NATIVE", "1").lower() in ("0", "false"):
        return None
    src = os.path.join(_DIR, f"{name}.cpp")
    so = os.path.join(_DIR, f"_{name}.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        tmp = f"{so}.{os.getpid()}.tmp"  # concurrent builders can't collide
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
                 "-o", tmp],
                check=True, capture_output=True, text=True, timeout=120)
            os.replace(tmp, so)
            log.info("built native %s", so)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                OSError) as exc:
            detail = getattr(exc, "stderr", "") or str(exc)
            log.warning("native build of %s failed (%s); using the Python "
                        "implementation", name, detail[:500])
            try:
                os.unlink(tmp)  # pid-unique names would otherwise accumulate
            except OSError:
                pass
            return None
    try:
        return ctypes.CDLL(so)
    except OSError as exc:
        log.warning("could not load %s (%s); using the Python "
                    "implementation", so, exc)
        return None
