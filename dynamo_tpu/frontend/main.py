"""Frontend node: OpenAI HTTP ingress + discovery + preprocessor + router.

Capability parity with reference components/frontend (main.py:24-268 —
``python -m dynamo.frontend``): one process packaging the HTTP service, model
watcher (auto-discovery of workers via the control plane), tokenization, and
routing. Run as ``python -m dynamo_tpu.frontend``.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses

from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.recorder import configure_ledger
from dynamo_tpu.runtime import flight, journal, slo
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.overload import AdaptiveLimiter

log = get_logger("frontend")


def init_observability(cfg: RuntimeConfig, runtime) -> None:
    """Arm the SLO plane, the accounting ledger, the fleet journal, and
    the flight recorder's bundle context for this process (shared by
    the frontend and launcher entrypoints)."""
    plane = slo.configure(cfg.slo, metrics=runtime.metrics)
    configure_ledger(cfg.slo.request_ring,
                     cfg.slo.request_log_path or None)
    # Decision plane (runtime/journal.py): attribute this process's
    # events to its instance id so cause refs are fleet-unique.
    journal.configure(worker=f"{runtime.instance_id:x}",
                      metrics=runtime.metrics)
    flight.configure(metrics=runtime.metrics,
                     config_fingerprint=dataclasses.asdict(cfg))
    # A fast-burn SLO page freezes the flight ring and captures a
    # diagnostic bundle (runtime/flight.py).
    plane.on_page(flight.on_slo_page)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="dynamo-tpu OpenAI frontend")
    parser.add_argument("--http-host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--router-mode", default="round_robin",
                        choices=["round_robin", "random", "kv"],
                        help="worker selection policy (kv = KV-cache-aware; "
                             "requires dynamo_tpu.llm.kv_router)")
    parser.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    parser.add_argument("--kv-router-temperature", type=float, default=0.0)
    parser.add_argument("--no-kv-federation", action="store_true",
                        help="score candidates by the local radix index "
                             "only (disable the inventory-sketch overlap "
                             "union; docs/OBSERVABILITY.md 'KV "
                             "federation')")
    parser.add_argument("--busy-threshold", type=float, default=None,
                        help="reject (503) when all workers exceed this load")
    # Overload defense (runtime/overload.py; docs/RESILIENCE.md):
    # adaptive admission + deadline-aware shedding + brownout on the
    # HTTP ingress, per-worker circuit breakers on the request plane.
    # Fine-grained knobs via DTPU_OVERLOAD_* env / [overload] TOML.
    parser.add_argument("--no-overload-defense", action="store_true",
                        help="disable adaptive admission/shedding on the "
                             "HTTP ingress (breakers stay governed by "
                             "DTPU_OVERLOAD_BREAKER_ENABLED)")
    parser.add_argument("--overload-target-ms", type=float, default=None,
                        help="AIMD per-phase (TTFT) latency target the "
                             "admission limit adapts against")
    parser.add_argument("--overload-max-concurrency", type=int, default=None)
    parser.add_argument("--default-deadline-ms", type=float, default=None,
                        help="server default when a request carries no "
                             "x-request-deadline-ms header")
    # SLO plane (runtime/slo.py; docs/OBSERVABILITY.md "SLO plane"):
    # targets default off; fine-grained knobs via DTPU_SLO_* / [slo] TOML.
    parser.add_argument("--no-slo", action="store_true",
                        help="disable the SLO plane (SLIs, burn-rate "
                             "alerts, /debug/slo)")
    parser.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                        help="TTFT target: 99%% of requests must reach "
                             "their first token within this budget")
    parser.add_argument("--slo-itl-p99-ms", type=float, default=None,
                        help="ITL target: 99%% of inter-token gaps under "
                             "this budget")
    parser.add_argument("--slo-error-rate", type=float, default=None,
                        help="availability target: max fraction of "
                             "requests that may fail (e.g. 0.001)")
    parser.add_argument("--request-log", default=None,
                        help="append per-request accounting records as "
                             "JSONL here (scripts/slo_report.py rolls "
                             "them up)")
    # Synthetic canary probing (llm/canary.py; docs/OBSERVABILITY.md
    # "Decision plane"): fine-grained knobs via DTPU_CANARY_* env.
    parser.add_argument("--canary", action="store_true",
                        help="probe every worker with tiny known-answer "
                             "greedy requests; repeated failures eject "
                             "the worker via its circuit breaker before "
                             "user traffic hits it")
    parser.add_argument("--canary-interval-s", type=float, default=None,
                        help="seconds between canary probe sweeps")
    parser.add_argument("--canary-ttft-bound-ms", type=float, default=None,
                        help="a canary first token slower than this "
                             "fails the probe")
    parser.add_argument("--canary-gate-joins", action="store_true",
                        help="canary-gated admission: a joining worker "
                             "(standby promote, fresh pod) is held on "
                             "breaker probation — zero user traffic — "
                             "until a canary probe chain passes "
                             "(docs/RESILIENCE.md \"Autoscaling\")")
    parser.add_argument("--coordinator-url", default=None)
    parser.add_argument("--grpc-port", type=int, default=None,
                        help="also serve the KServe v2 gRPC inference "
                             "service on this port")
    parser.add_argument("--tls-cert-path", default=None,
                        help="serve HTTPS with this certificate chain "
                             "(reference frontend TLS flags; needs "
                             "--tls-key-path too)")
    parser.add_argument("--tls-key-path", default=None)
    return parser.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)

    kv_router_factory = None
    if args.router_mode == "kv":
        from dynamo_tpu.llm.kv_router import make_kv_router_factory

        kv_router_factory = make_kv_router_factory(
            overlap_score_weight=args.kv_overlap_score_weight,
            temperature=args.kv_router_temperature,
            busy_threshold=args.busy_threshold,
            federation=not args.no_kv_federation)

    manager = ModelManager()
    watcher = ModelWatcher(runtime, manager, router_mode=args.router_mode,
                           kv_router_factory=kv_router_factory)
    ov = cfg.overload
    if args.no_overload_defense:
        ov.enabled = False
    if args.overload_target_ms is not None:
        ov.target_latency_ms = args.overload_target_ms
    if args.overload_max_concurrency is not None:
        ov.max_concurrency = args.overload_max_concurrency
    if args.default_deadline_ms is not None:
        ov.default_deadline_ms = args.default_deadline_ms
    limiter = (AdaptiveLimiter(ov, metrics=runtime.metrics)
               if ov.enabled else None)
    if args.no_slo:
        cfg.slo.enabled = False
    if args.slo_ttft_p99_ms is not None:
        cfg.slo.ttft_p99_ms = args.slo_ttft_p99_ms
    if args.slo_itl_p99_ms is not None:
        cfg.slo.itl_p99_ms = args.slo_itl_p99_ms
    if args.slo_error_rate is not None:
        cfg.slo.error_rate = args.slo_error_rate
    if args.request_log is not None:
        cfg.slo.request_log_path = args.request_log
    # Observability (incl. the journal's worker identity) arms BEFORE
    # discovery starts: the first worker_join events must already carry
    # this process's id, not the "proc" placeholder.
    init_observability(cfg, runtime)
    await watcher.start()
    # Decision plane: merge the fleet's journal deltas into one causal
    # timeline (llm/timeline.py) served at GET /debug/timeline, and arm
    # the synthetic canary prober when asked.
    from dynamo_tpu.llm.canary import (CanaryConfig, CanaryProber,
                                       apply_canary_env)
    from dynamo_tpu.llm.timeline import TimelineCollector
    collector = TimelineCollector(runtime)
    await collector.start()
    canary_cfg = apply_canary_env(CanaryConfig())
    if args.canary:
        canary_cfg.enabled = True
    if args.canary_interval_s is not None:
        canary_cfg.interval_s = args.canary_interval_s
    if args.canary_ttft_bound_ms is not None:
        canary_cfg.ttft_bound_ms = args.canary_ttft_bound_ms
    if args.canary_gate_joins:
        canary_cfg.enabled = True
        canary_cfg.gate_joins = True
    canary = (CanaryProber(manager, canary_cfg, metrics=runtime.metrics)
              if canary_cfg.enabled else None)
    if canary is not None:
        # Fleet-membership hooks: joins go on probation until a probe
        # chain passes (gate_joins), leaves clear probe state so a
        # rejoining worker starts clean.
        watcher.on_join = canary.note_join
        watcher.on_leave = canary.note_leave
    service = HttpService(runtime, manager, args.http_host, args.http_port,
                          tls_cert_path=args.tls_cert_path,
                          tls_key_path=args.tls_key_path,
                          overload=limiter)
    service.timeline_provider = collector.timeline_status
    await service.start()
    if canary is not None:
        canary.start()
    grpc_server = None
    if args.grpc_port is not None:
        from dynamo_tpu.grpc.kserve import make_server
        grpc_server, bound = make_server(manager, args.http_host,
                                         args.grpc_port)
        await grpc_server.start()
        log.info("KServe gRPC service on %s:%d", args.http_host, bound)

    import signal
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, runtime.shutdown)
        except NotImplementedError:
            pass
    try:
        await runtime.wait_for_shutdown()
    finally:
        if grpc_server is not None:
            await grpc_server.stop(grace=2)
        if canary is not None:
            await canary.stop()
        await collector.stop()
        await service.stop()
        await watcher.stop()
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
