"""Mocker worker: serves the engine simulator as a registered model.

``python -m dynamo_tpu.backends.mocker`` (reference parity:
components/backends/mocker + `dynamo-run out=mocker`): exercises KV-aware
routing, overload, and disagg logic with zero TPUs.

Role-reconfigurable (llm/reconfig.py): ``--mode prefill|decode|agg``
picks the LAUNCH role, and a ``SetRole`` directive (planner or the
status server's POST /control/role) flips the worker live — the mocker
is how the role-transition protocol is chaos-tested without hardware
(tests/test_reconfig.py, scripts/check.sh reconfig smoke). The mocker's
"prefill" role registers the same simulator under the prefill component
(the registration/drain/rewire mechanics are real; the KV parcels are
exercised by the TPU engine's disagg tests).
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.kv_router.publisher import (KvEventPublisher,
                                                KvInventoryPublisher,
                                                WorkerMetricsPublisher)
from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine
from dynamo_tpu.llm.model_card import (ModelRuntimeConfig, deregister_llm,
                                       register_llm)
from dynamo_tpu.llm.reconfig import ROLES, RoleManager, ServingProfile
from dynamo_tpu.llm.standby import ScaleAgent
from dynamo_tpu.llm.tokenizer import Tokenizer, make_test_tokenizer
from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.journal import JournalPublisher


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="dynamo-tpu mocker worker")
    parser.add_argument("--model-name", default="mock-model")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--component", default="mocker")
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument("--num-kv-blocks", type=int, default=1024)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--max-num-seqs", type=int, default=64)
    parser.add_argument("--speedup-ratio", type=float, default=1.0)
    parser.add_argument("--host-blocks", type=int, default=0,
                        help="simulated host (G2) tier capacity: evicted "
                             "blocks demote here, stay in the inventory "
                             "digest, and serve peer pulls over the KV "
                             "plane (federation testing without TPUs)")
    parser.add_argument("--kv-plane", action="store_true",
                        help="run a KV plane server + G4 remote source "
                             "on this mocker (peer block pulls)")
    parser.add_argument("--migration-limit", type=int, default=0)
    parser.add_argument("--coordinator-url", default=None)
    parser.add_argument("--mode", default="agg", choices=list(ROLES),
                        help="launch role; runtime-reconfigurable via "
                             "SetRole (llm/reconfig.py)")
    parser.add_argument("--standby", action="store_true",
                        help="park as a pre-warmed standby: simulator "
                             "ready but DEREGISTERED, announced on a "
                             "standby/ lease key, joining the serving "
                             "fleet only on a planner promote "
                             "directive (llm/standby.py)")
    parser.add_argument("--prefill-component", default="prefill",
                        help="component the prefill role registers under")
    parser.add_argument("--lora", action="append", default=[],
                        metavar="NAME",
                        help="register NAME as a served LoRA adapter "
                             "name riding this mocker's base model "
                             "(repeatable; the simulator ignores the "
                             "adapter — this exercises the frontend "
                             "resolution / routing / accounting path "
                             "without TPUs)")
    return parser.parse_args(argv)


def make_profile_builder(runtime, engine, args, tokenizer):
    """Per-role serving profiles around ONE simulator engine — the
    mocker mirror of the TPU worker's profile builder."""

    async def build(role: str) -> ServingProfile:
        prof = ServingProfile(role)
        if role == "prefill":
            endpoint = (runtime.namespace(None)
                        .component(args.prefill_component)
                        .endpoint(args.endpoint))
            server = await endpoint.serve_endpoint(engine.handler(),
                                                   graceful_shutdown=True)
            prof.add_server(server)
            return prof
        # decode/agg: the routable model endpoint + its model card.
        endpoint = (runtime.namespace(None).component(args.component)
                    .endpoint(args.endpoint))
        server = await endpoint.serve_endpoint(engine.handler(),
                                               graceful_shutdown=False)
        prof.add_server(server)
        await register_llm(
            runtime, endpoint, args.model_name, tokenizer,
            kv_cache_block_size=args.block_size,
            migration_limit=args.migration_limit,
            runtime_config=ModelRuntimeConfig(
                total_kv_blocks=args.num_kv_blocks,
                max_num_seqs=args.max_num_seqs))
        prof.add_closer(
            "model-card", lambda: deregister_llm(runtime, args.model_name))
        from dynamo_tpu.llm.model_card import register_adapter
        for lname in getattr(args, "lora", None) or []:
            await register_adapter(
                runtime, endpoint, lname, args.model_name, tokenizer,
                kv_cache_block_size=args.block_size,
                migration_limit=args.migration_limit)
            prof.add_closer(f"adapter-card-{lname}",
                            lambda n=lname: deregister_llm(runtime, n))
        return prof

    return build


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)
    try:
        tokenizer = (Tokenizer.from_file(args.tokenizer) if args.tokenizer
                     else make_test_tokenizer())
        mocker_cfg = MockerConfig(
            num_kv_blocks=args.num_kv_blocks, block_size=args.block_size,
            max_num_seqs=args.max_num_seqs, speedup_ratio=args.speedup_ratio,
            host_blocks=args.host_blocks)
        ns = cfg.namespace
        kv_pub = KvEventPublisher(runtime, ns, args.component,
                                  runtime.instance_id)
        metrics_pub = WorkerMetricsPublisher(runtime, ns, args.component,
                                             runtime.instance_id)
        inventory_pub = KvInventoryPublisher(runtime, ns, args.component,
                                             runtime.instance_id)
        engine = MockerEngine(mocker_cfg, kv_pub, metrics_pub,
                              inventory_publisher=inventory_pub)
        inventory_pub.start_periodic(engine.inventory_digest)
        plane = None
        peer_watch_task = None
        if args.kv_plane:
            # Same kvplane/ registration + peer-watch contract as the
            # TPU worker (backends/tpu.py), mocker-scale: the plane
            # serves this worker's sim blocks, the remote source pulls
            # peers' — KV federation end to end with zero TPUs.
            from dynamo_tpu.llm.kv_plane import (KvPlaneServer,
                                                 RemoteBlockSource)
            plane = KvPlaneServer(block_provider=engine.host_block_provider)
            plane.start()
            coordinator = runtime.require_coordinator()
            await coordinator.kv_put(
                f"kvplane/{ns}/{runtime.instance_id:x}",
                {"addr": plane.address, "model": args.model_name},
                lease_id=coordinator.primary_lease_id)
            engine.remote_source = RemoteBlockSource(self_addr=plane.address)

            async def watch_peers() -> None:
                watch = await coordinator.watch_prefix(f"kvplane/{ns}/")
                peers = {item["k"]: item["v"]["addr"]
                         for item in watch.snapshot
                         if item["v"].get("model") == args.model_name}
                engine.remote_source.peers = [
                    a for a in peers.values() if a != plane.address]
                async for event in watch:
                    if event["event"] == "put" and \
                            event["value"].get("model") == args.model_name:
                        peers[event["key"]] = event["value"]["addr"]
                    elif event["event"] == "delete":
                        gone = peers.pop(event["key"], None)
                        if gone is not None:
                            # worker_leave/scale-in: drop the peer AND
                            # its breaker state now, not at TTL.
                            engine.remote_source.drop_peer(gone)
                    engine.remote_source.peers = [
                        a for a in peers.values() if a != plane.address]

            peer_watch_task = asyncio.create_task(watch_peers())
        # Decision plane: this worker's journal (role flips, preempts,
        # breaker views) rides the event plane into the frontend's
        # merged /debug/timeline.
        journal.configure(worker=f"{runtime.instance_id:x}",
                          metrics=runtime.metrics)
        journal_pub = JournalPublisher(runtime.require_coordinator(), ns,
                                       f"{runtime.instance_id:x}")
        journal_pub.start_periodic()
        roles = RoleManager(runtime,
                            make_profile_builder(runtime, engine, args,
                                                 tokenizer),
                            role=args.mode,
                            status_extra={"backend": "mocker",
                                          "model": args.model_name})
        # Autoscaling: every worker answers scale directives (retire);
        # --standby parks warm and deregistered until a promote.
        scale_agent = ScaleAgent(
            runtime, roles, standby=args.standby,
            status_extra={"backend": "mocker", "model": args.model_name},
            metrics=runtime.metrics)
        if not args.standby:
            await roles.start()
        await scale_agent.start()
        engine.start()
        status_server = None
        if cfg.system_enabled:
            from dynamo_tpu.llm.fleet import register_status_server
            from dynamo_tpu.runtime.health import SystemStatusServer
            status_server = SystemStatusServer(runtime, host=cfg.bind_host,
                                               port=cfg.system_port,
                                               role_manager=roles,
                                               kv_provider=engine.kv_status,
                                               perf_provider=engine.perf_status,
                                               scale_agent=scale_agent)
            await status_server.start()
            await register_status_server(
                runtime, status_server.port,
                extra={"backend": "mocker", "component": args.component,
                       "model": args.model_name})
        port = (roles.profile.servers[0].port
                if roles.profile and roles.profile.servers else 0)
        mode = "standby" if args.standby else args.mode
        print(f"MOCKER_READY mode={mode} port={port} "
              f"worker={runtime.instance_id:x}", flush=True)
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        await runtime.wait_for_shutdown()
        journal_pub.stop_periodic()
        inventory_pub.stop_periodic()
        if peer_watch_task is not None:
            peer_watch_task.cancel()
        if plane is not None:
            plane.close()
        await engine.stop()
        if status_server is not None:
            await status_server.stop()
        await scale_agent.stop()
        await roles.stop()
    finally:
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
