"""Mocker worker: serves the engine simulator as a registered model.

``python -m dynamo_tpu.backends.mocker`` (reference parity:
components/backends/mocker + `dynamo-run out=mocker`): exercises KV-aware
routing, overload, and disagg logic with zero TPUs.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine
from dynamo_tpu.llm.model_card import ModelRuntimeConfig, register_llm
from dynamo_tpu.llm.tokenizer import Tokenizer, make_test_tokenizer
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="dynamo-tpu mocker worker")
    parser.add_argument("--model-name", default="mock-model")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--component", default="mocker")
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument("--num-kv-blocks", type=int, default=1024)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--max-num-seqs", type=int, default=64)
    parser.add_argument("--speedup-ratio", type=float, default=1.0)
    parser.add_argument("--migration-limit", type=int, default=0)
    parser.add_argument("--coordinator-url", default=None)
    return parser.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)
    try:
        tokenizer = (Tokenizer.from_file(args.tokenizer) if args.tokenizer
                     else make_test_tokenizer())
        mocker_cfg = MockerConfig(
            num_kv_blocks=args.num_kv_blocks, block_size=args.block_size,
            max_num_seqs=args.max_num_seqs, speedup_ratio=args.speedup_ratio)
        ns = cfg.namespace
        kv_pub = KvEventPublisher(runtime, ns, args.component,
                                  runtime.instance_id)
        metrics_pub = WorkerMetricsPublisher(runtime, ns, args.component,
                                             runtime.instance_id)
        engine = MockerEngine(mocker_cfg, kv_pub, metrics_pub)
        endpoint = (runtime.namespace(None).component(args.component)
                    .endpoint(args.endpoint))
        server = await endpoint.serve_endpoint(engine.handler(),
                                               graceful_shutdown=False)
        await register_llm(
            runtime, endpoint, args.model_name, tokenizer,
            kv_cache_block_size=args.block_size,
            migration_limit=args.migration_limit,
            runtime_config=ModelRuntimeConfig(
                total_kv_blocks=args.num_kv_blocks,
                max_num_seqs=args.max_num_seqs))
        engine.start()
        print(f"MOCKER_READY port={server.port} worker={runtime.instance_id:x}",
              flush=True)
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        await runtime.wait_for_shutdown()
        await engine.stop()
        await server.shutdown()
    finally:
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
