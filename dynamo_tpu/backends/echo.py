"""Echo worker: serves the echo engine as a registered model.

``python -m dynamo_tpu.backends.echo --model-name echo`` — the minimum
end-to-end worker (reference parity: dynamo-run out=echo, engines.rs EchoFull).
Uses a built-in test tokenizer unless --tokenizer points at a tokenizer.json.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.engines import EchoEngine
from dynamo_tpu.llm.model_card import register_llm
from dynamo_tpu.llm.tokenizer import Tokenizer, make_test_tokenizer
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="dynamo-tpu echo worker")
    parser.add_argument("--model-name", default="echo")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument("--component", default="echo")
    parser.add_argument("--tokenizer", default=None,
                        help="path to a tokenizer.json (default: built-in test tokenizer)")
    parser.add_argument("--token-delay", type=float, default=0.0)
    parser.add_argument("--migration-limit", type=int, default=0)
    parser.add_argument("--coordinator-url", default=None)
    return parser.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)
    try:
        tokenizer = (Tokenizer.from_file(args.tokenizer) if args.tokenizer
                     else make_test_tokenizer())
        engine = EchoEngine(token_delay_s=args.token_delay)
        endpoint = (runtime.namespace(None).component(args.component)
                    .endpoint(args.endpoint))
        server = await endpoint.serve_endpoint(engine.handler(),
                                               graceful_shutdown=False)
        await register_llm(runtime, endpoint, args.model_name, tokenizer,
                           migration_limit=args.migration_limit)
        print(f"ECHO_WORKER_READY port={server.port}", flush=True)
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        await runtime.wait_for_shutdown()
        await server.shutdown()
    finally:
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
