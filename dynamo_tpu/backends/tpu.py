"""TPU worker: serves the JAX engine as a registered model.

``python -m dynamo_tpu.backends.tpu --model llama-3-8b`` — the TPU-native
equivalent of the reference's vLLM worker (components/backends/vllm/src/dynamo/
vllm/main.py, SURVEY.md call stack 3.2): starts the engine, registers the
model with its runtime config, serves the endpoint, publishes KV events +
ForwardPassMetrics.

Disaggregated serving (reference handlers.py:113-199, SURVEY.md call stack
3.3): ``--mode prefill`` serves a prefill-only endpoint (computes prompt KV,
streams it back as a chunked parcel + first token); ``--mode decode``
conditionally forwards long prompts to discovered prefill workers
(``--max-local-prefill-length``, reference disagg_router.rs:25-45), injects
the transferred KV, and decodes. ``--mode agg`` (default) is fully local.
Handlers live in dynamo_tpu.llm.disagg; e2e-tested in tests/test_disagg.py.

``--mode`` is only the LAUNCH role: the worker is runtime-reconfigurable
via the SetRole protocol (llm/reconfig.py) — a planner directive or the
status server's POST /control/role drains in-flight streams through the
retire/migration machinery and rebuilds the serving profile around the
same engine, no weight reload (docs/RESILIENCE.md "Role transitions").

Multi-node (reference engines.rs:31-44 MultiNodeConfig): ``--num-nodes N
--node-rank R`` alone coordinates a per-host replica group over the
leader/worker barrier. With ``JAX_COORDINATOR_ADDRESS=host:port`` it
instead runs ONE engine whose mesh spans every host's chips
(multi-controller SPMD): rank 0 serves and publishes its device-dispatch
stream, ranks >0 replay it (engine/multihost.py); e2e-tested in
tests/test_multihost.py.
"""

from __future__ import annotations

import argparse
import asyncio
import os

from dynamo_tpu.engine.config import EngineConfig, PRESETS, ModelSpec
from dynamo_tpu.engine.engine import TPUEngine
from dynamo_tpu.llm.kv_router.publisher import (KvEventPublisher,
                                                KvInventoryPublisher,
                                                WorkerMetricsPublisher)
from dynamo_tpu.llm.model_card import ModelRuntimeConfig, register_llm
from dynamo_tpu.llm.tokenizer import Tokenizer, make_test_tokenizer
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("tpu_worker")


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="dynamo-tpu TPU engine worker")
    parser.add_argument("--model", default="tiny-test",
                        help="preset name or path to a HF model dir")
    parser.add_argument("--model-name", default=None,
                        help="served model name (default: preset/dir name)")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--component", default="tpu")
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--num-pages", type=int, default=None)
    parser.add_argument("--max-num-seqs", type=int, default=32)
    parser.add_argument("--max-pages-per-seq", type=int, default=512)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1,
                        help="layer-sharded pipeline axis")
    parser.add_argument("--sp", type=int, default=1,
                        help="sequence (context) parallelism for prefill")
    parser.add_argument("--pp-microbatch", action="store_true",
                        help="with --pp > 1: microbatched pipeline-"
                             "parallel prefill (GPipe fill/drain over the "
                             "pp stages) instead of layer-sharded-only")
    parser.add_argument("--ring-attention", action="store_true",
                        help="with --sp > 1: rotate K/V blocks around "
                             "the sp ring (ppermute + online softmax) "
                             "instead of all-gathering the full K/V — "
                             "peak K/V memory is one block per device")
    parser.add_argument("--decode-window", default="auto",
                        type=_window_arg,
                        help="decode steps per dispatched window: a "
                             "positive int, or 'auto' to size from the "
                             "model's weight-read step estimate "
                             "(DTPU_WINDOW_TARGET_MS)")
    parser.add_argument("--pipeline-depth", type=int, default=4,
                        help="decode windows in flight before the host "
                             "blocks on the oldest readback")
    parser.add_argument("--prefill-chunk-tokens", default="auto",
                        type=_chunk_arg,
                        help="stall-free chunked prefill: prompt tokens "
                             "dispatched as prefill chunks per engine-loop "
                             "iteration before the next decode window; "
                             "'auto' sizes one chunk to ~one "
                             "DTPU_WINDOW_TARGET_MS window period "
                             "(DTPU_PREFILL_CHUNK_TOKENS overrides)")
    parser.add_argument("--warmup-prefill-ladder", action="store_true",
                        help="pre-compile EVERY prefill bucket incl. the "
                             "with-history chunk variants at startup, so "
                             "the first long prompt never pays per-bucket "
                             "XLA compiles while decode slots wait")
    parser.add_argument("--attention-backend", default="auto",
                        choices=["auto", "pallas", "xla"])
    parser.add_argument("--quant", default=None, choices=["int8"],
                        help="weight-only quantization: int8 storage, "
                             "bf16 MXU compute (halves weight HBM — fits "
                             "full llama-3-8b on one 16 GB v5e)")
    parser.add_argument("--quant-kv", default=None, choices=["int8"],
                        help="KV-cache quantization: int8 pages with "
                             "per-token scales, dequant fused into the "
                             "attention kernels — ~2x KV pages per HBM "
                             "GB and ~half the attention/transfer bytes; "
                             "composes with --quant (DTPU_QUANT_KV "
                             "overrides)")
    parser.add_argument("--host-cache-pages", type=int, default=0,
                        help="G2 host-DRAM KV block cache capacity in "
                             "pages (0 = disabled); evicted HBM pages "
                             "offload here and onboard on prefix hits")
    parser.add_argument("--kv-disk-cache-dir", default=None,
                        help="G3 disk tier directory behind the host cache")
    parser.add_argument("--kv-watermarks", default=None,
                        help="KVBM proactive demotion watermarks "
                             "'low,high' as fractions of the HBM pool "
                             "free list (engine/kvbm.py): below low, LRU "
                             "inactive blocks demote to the host tier "
                             "until high (hysteresis); needs "
                             "--host-cache-pages (DTPU_KV_WATERMARKS "
                             "overrides)")
    parser.add_argument("--lora", action="append", default=[],
                        metavar="NAME=PATH",
                        help="serve a LoRA adapter: NAME becomes a "
                             "registered model name riding this "
                             "worker's base model; PATH is a HF PEFT "
                             "checkpoint dir (adapter_config.json + "
                             "adapter_model.safetensors). Repeatable — "
                             "heterogeneous adapters batch into one "
                             "decode window (engine/lora.py)")
    parser.add_argument("--max-adapters", type=int, default=None,
                        help="resident device adapter slots (default: "
                             "max(4, number of --lora flags)); registered "
                             "adapters beyond this hot-load on demand "
                             "with LRU eviction")
    parser.add_argument("--max-lora-rank", type=int, default=8,
                        help="adapter ranks pad to this fixed max so "
                             "stacks keep static shapes (checkpoints "
                             "with a larger rank are rejected)")
    parser.add_argument("--spec-decode", default=None, choices=["ngram"],
                        help="speculative decoding: 'ngram' = prompt-"
                             "lookup self-drafting verified in-window; "
                             "serves greedy and temperature/top-k/top-p/"
                             "seeded sampling (on-device rejection "
                             "sampling keeps the exact output "
                             "distribution); logprobs and penalties "
                             "are not supported under spec decode")
    parser.add_argument("--spec-k", type=int, default=3,
                        help="drafts verified per speculative step")
    parser.add_argument("--ttft-budget-ms", type=float, default=None,
                        help="SLA-aware admission: defer admitting cold "
                             "prefills while the projected TTFT (measured "
                             "prefill rate x cold-token backlog) exceeds "
                             "this budget")
    parser.add_argument("--admission-reject-factor", type=float, default=2.0,
                        help="with --ttft-budget-ms: reject (503) requests "
                             "whose projected TTFT through the backlog "
                             "exceeds budget x this factor, so the router "
                             "retries another worker; 0 = queue unboundedly")
    parser.add_argument("--migration-limit", type=int, default=0)
    parser.add_argument("--tool-call-parser", default=None,
                        help="tool-call format on the backward edge "
                             "(hermes, llama3_json, mistral, nemotron_deci, "
                             "phi4, default)")
    parser.add_argument("--reasoning-parser", default=None,
                        help="think-tag splitting (deepseek_r1, basic)")
    parser.add_argument("--coordinator-url", default=None)
    parser.add_argument("--mode", default="agg",
                        choices=["agg", "prefill", "decode"],
                        help="agg = fully local; prefill = prefill-only "
                             "worker (serves KV parcels); decode = decode "
                             "worker forwarding long prompts to prefill "
                             "workers")
    parser.add_argument("--standby", action="store_true",
                        help="park as a pre-warmed standby: weights "
                             "loaded and warmup run, but DEREGISTERED "
                             "— announced on a standby/ lease key and "
                             "joining the serving fleet in seconds on "
                             "a planner promote directive "
                             "(llm/standby.py; docs/RESILIENCE.md "
                             "\"Autoscaling\")")
    parser.add_argument("--max-local-prefill-length", type=int, default=512,
                        help="decode mode: prompts longer than this prefill "
                             "remotely (conditional disaggregation; dynamic "
                             "via the coordinator disagg/<model> key)")
    parser.add_argument("--prefill-dispatch", default="direct",
                        choices=["direct", "queue"],
                        help="remote-prefill dispatch: direct round-robin "
                             "to discovered prefill workers, or the shared "
                             "coordinator queue with worker-side pull and "
                             "depth backpressure (reference PrefillQueue, "
                             "nats.rs:433)")
    parser.add_argument("--max-prefill-queue-depth", type=int, default=8,
                        help="queue dispatch: enqueue only while the queue "
                             "is shallower than this; otherwise prefill "
                             "locally (load-leveling backpressure)")
    parser.add_argument("--prefill-component", default=None,
                        help="component name prefill workers serve under "
                             "(default: 'prefill')")
    parser.add_argument("--kv-plane-host", default="127.0.0.1",
                        help="address this worker's direct KV data plane "
                             "binds and advertises (the NIXL-role bulk "
                             "plane, llm/kv_plane.py); must be reachable "
                             "by peer workers")
    parser.add_argument("--no-kv-plane", action="store_true",
                        help="disable the direct KV data plane: disagg "
                             "parcels ride the request plane inline (v0 "
                             "fallback) and this worker serves no G4 "
                             "remote-tier blocks")
    parser.add_argument("--num-nodes", type=int, default=1,
                        help="hosts in this worker group; >1 gates serving "
                             "on a leader/worker barrier (rank 0 leads) so "
                             "all replicas agree on model + mesh shape "
                             "before any serves")
    parser.add_argument("--node-rank", type=int, default=0)
    parser.add_argument("--mh-group", default=None,
                        help="multi-host group id (default: model name). "
                             "REQUIRED to be distinct per group when two "
                             "multi-host groups of the same model share a "
                             "coordinator — it keys the dispatch stream "
                             "and bring-up barrier")
    return parser.parse_args(argv)


def build_engine_config(args) -> EngineConfig:
    import dataclasses

    from dynamo_tpu.engine.hub import resolve_model
    try:
        spec, ckpt = resolve_model(args.model)
    except FileNotFoundError as exc:
        raise SystemExit(str(exc)) from exc
    if getattr(args, "quant", None):
        spec = dataclasses.replace(spec, quant=args.quant)
    args.resolved_checkpoint = ckpt
    return EngineConfig(
        model=spec, page_size=args.page_size, num_pages=args.num_pages,
        max_num_seqs=args.max_num_seqs, max_pages_per_seq=args.max_pages_per_seq,
        tp=args.tp, dp=args.dp, pp=getattr(args, "pp", 1),
        sp=getattr(args, "sp", 1),
        pp_microbatch=getattr(args, "pp_microbatch", False),
        ring_attention=getattr(args, "ring_attention", False),
        attention_backend=args.attention_backend,
        decode_window=_window_arg(getattr(args, "decode_window", "auto")),
        pipeline_depth=getattr(args, "pipeline_depth", 4),
        prefill_chunk_tokens=_chunk_arg(
            getattr(args, "prefill_chunk_tokens", "auto")),
        warmup_windows=True,
        warmup_prefill_ladder=getattr(args, "warmup_prefill_ladder", False),
        quant_kv=getattr(args, "quant_kv", None),
        host_cache_pages=args.host_cache_pages,
        kv_disk_cache_dir=args.kv_disk_cache_dir,
        kv_demote_low_watermark=_watermark_arg(
            getattr(args, "kv_watermarks", None))[0],
        kv_demote_high_watermark=_watermark_arg(
            getattr(args, "kv_watermarks", None))[1],
        max_adapters=_max_adapters_arg(args),
        lora_max_rank=getattr(args, "max_lora_rank", 8),
        spec_decode=getattr(args, "spec_decode", None),
        spec_k=getattr(args, "spec_k", 3),
        ttft_budget_ms=getattr(args, "ttft_budget_ms", None),
        admission_reject_factor=(
            getattr(args, "admission_reject_factor", 0.0)
            if getattr(args, "ttft_budget_ms", None) else 0.0))


def _lora_args(args) -> list[tuple[str, str]]:
    """Parse repeated --lora NAME=PATH flags."""
    out = []
    for item in getattr(args, "lora", None) or []:
        name, sep, path = str(item).partition("=")
        if not sep or not name or not path:
            raise SystemExit(f"--lora expects NAME=PATH, got {item!r}")
        out.append((name, path))
    return out


def _max_adapters_arg(args) -> int:
    explicit = getattr(args, "max_adapters", None)
    if explicit is not None:
        return explicit
    loras = _lora_args(args)
    return max(4, len(loras)) if loras else 0


def _watermark_arg(value) -> tuple[float, float]:
    """Parse --kv-watermarks 'low[,high]' (None -> disabled)."""
    if not value:
        return 0.0, 0.0
    parts = [p for p in str(value).replace(",", " ").split() if p]
    low = float(parts[0])
    high = float(parts[1]) if len(parts) > 1 else 0.0
    return low, high


def _window_arg(value) -> int | str:
    """argparse type for --decode-window: positive int or 'auto'.
    ValueError -> argparse's clean 'invalid value' error at parse time."""
    if value == "auto":
        return value
    n = int(value)
    if n < 1:
        raise ValueError(f"decode window must be >= 1, got {n}")
    return n


def _chunk_arg(value) -> int | str:
    """argparse type for --prefill-chunk-tokens: positive int or 'auto'."""
    if value == "auto":
        return value
    n = int(value)
    if n < 1:
        raise ValueError(f"prefill chunk tokens must be >= 1, got {n}")
    return n


def make_profile_builder(runtime, args, engine, engine_cfg, tokenizer,
                         model_name, plane, prefill_component):
    """Per-role serving profiles around ONE engine (llm/reconfig.py).

    The engine object — weights, KV pool, compiled programs — lives
    outside the profile and survives role flips; a flip only swaps what
    this worker REGISTERS and which role-specific machinery (prefill
    queue worker, disagg client + config watch, queue dispatcher) runs
    around it. This is the factory both launch (initial ``--mode``) and
    the SetRole protocol build through, so a flipped-to role is
    byte-for-byte the role it would have launched as.
    """
    from dynamo_tpu.llm.disagg import (
        PREFILL_ENDPOINT, DisaggDecodeHandler, DisaggRouterConfig,
        make_prefill_handler)
    from dynamo_tpu.llm.model_card import deregister_llm, register_adapter
    from dynamo_tpu.llm.reconfig import ServingProfile
    lora_names = [name for name, _ in _lora_args(args)]

    async def build(role: str) -> ServingProfile:
        prof = ServingProfile(role)
        if role == "prefill":
            # Prefill workers register under their own component so decode
            # workers (not the frontend router) discover them; prefill
            # drains gracefully on shutdown (reference vllm main.py:151-161).
            endpoint = (runtime.namespace(None).component(prefill_component)
                        .endpoint(PREFILL_ENDPOINT))
            server = await endpoint.serve_endpoint(
                make_prefill_handler(engine, plane=plane),
                graceful_shutdown=True)
            prof.add_server(server)
            if plane is not None:
                # Also pull from the shared prefill queue (queue dispatch
                # needs the data plane for the reply ticket): serving both
                # paths lets direct- and queue-mode decode workers share
                # one prefill pool. A drain pauses the pull loop first so
                # queued prompts go to peers.
                from dynamo_tpu.llm.prefill_queue import QueuePrefillWorker
                queue_worker = QueuePrefillWorker(
                    engine, runtime.require_coordinator(), model_name,
                    plane)
                queue_worker.start()
                prof.add_pausable(queue_worker)
                prof.add_closer("prefill-queue", queue_worker.stop)
            else:
                log.warning(
                    "--no-kv-plane: this prefill worker will NOT pull "
                    "from the shared prefill queue (queue replies carry "
                    "data-plane tickets); queue-mode decode workers need "
                    "at least one plane-enabled prefill worker")
            return prof
        if role == "decode":
            prefill_ep = (runtime.namespace(None)
                          .component(prefill_component)
                          .endpoint(PREFILL_ENDPOINT))
            prefill_client = await prefill_ep.client()
            disagg_cfg = await DisaggRouterConfig.from_coordinator_with_watch(
                runtime.require_coordinator(), model_name,
                default_max_local=args.max_local_prefill_length)
            disagg_handler = DisaggDecodeHandler(engine, prefill_client,
                                                 disagg_cfg)
            if args.prefill_dispatch == "queue":
                from dynamo_tpu.llm.prefill_queue import (
                    QueuePrefillDispatcher)
                # Share the handler's plane client: one TCP connection
                # cache per prefill worker, one close at teardown.
                disagg_handler.queue_dispatcher = QueuePrefillDispatcher(
                    runtime.require_coordinator(), model_name,
                    disagg_handler.plane_client,
                    max_queue_depth=args.max_prefill_queue_depth)
            handler = disagg_handler.handler()
            prof.add_closer("prefill-client", prefill_client.close)
            prof.add_closer("disagg-config", disagg_cfg.close)

            async def _close_plane_client(h=disagg_handler):
                h.plane_client.close()

            prof.add_closer("plane-client", _close_plane_client)
        else:
            handler = engine.handler()
        endpoint = (runtime.namespace(None).component(args.component)
                    .endpoint(args.endpoint))
        server = await endpoint.serve_endpoint(handler,
                                               graceful_shutdown=False)
        prof.add_server(server)
        await register_llm(
            runtime, endpoint, model_name, tokenizer,
            context_length=engine_cfg.max_model_len,
            kv_cache_block_size=engine_cfg.page_size,
            migration_limit=args.migration_limit,
            tool_call_parser=args.tool_call_parser,
            reasoning_parser=args.reasoning_parser,
            runtime_config=ModelRuntimeConfig(
                total_kv_blocks=engine.runner.num_pages,
                max_num_seqs=engine_cfg.max_num_seqs,
                # The frontend's audio encoder projects to this width
                # (mm_embeds spans must match the model hidden size).
                # expected_roofline_frac: the perf expectation doctor
                # compares live perf_roofline_frac against.
                extra={"hidden_size": engine_cfg.model.hidden_size,
                       "expected_roofline_frac":
                           engine_cfg.expected_roofline_frac}))
        prof.add_closer("model-card",
                        lambda: deregister_llm(runtime, model_name))
        # LoRA adapters register as served names riding THIS endpoint
        # (adapter-aware model cards: the frontend resolves the OpenAI
        # model field to (base, adapter) from the card's extras). They
        # deregister with the base card on drains/role flips — a
        # prefill-only worker must not advertise adapter names either.
        for lname in lora_names:
            await register_adapter(
                runtime, endpoint, lname, model_name, tokenizer,
                context_length=engine_cfg.max_model_len,
                kv_cache_block_size=engine_cfg.page_size,
                migration_limit=args.migration_limit,
                tool_call_parser=args.tool_call_parser,
                reasoning_parser=args.reasoning_parser,
                runtime_config=ModelRuntimeConfig(
                    total_kv_blocks=engine.runner.num_pages,
                    max_num_seqs=engine_cfg.max_num_seqs,
                    extra={"hidden_size": engine_cfg.model.hidden_size}))
            prof.add_closer(f"adapter-card-{lname}",
                            lambda n=lname: deregister_llm(runtime, n))
        return prof

    return build


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    # Multi-host SINGLE engine (one jax.distributed mesh spanning hosts):
    # gated on JAX_COORDINATOR_ADDRESS + --num-nodes. Must initialize
    # before any JAX backend use.
    mh_addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    multihost_engine = args.num_nodes > 1 and bool(mh_addr)
    if multihost_engine:
        from dynamo_tpu.engine import multihost
        multihost.initialize(mh_addr, args.num_nodes, args.node_rank)
    runtime = await DistributedRuntime.from_settings(cfg)
    try:
        engine_cfg = build_engine_config(args)
        model_name = args.model_name or engine_cfg.model.name
        ckpt = args.resolved_checkpoint
        if args.tokenizer:
            tokenizer = Tokenizer.from_file(args.tokenizer)
        elif ckpt is not None:
            tokenizer = Tokenizer.from_pretrained_dir(ckpt)
        else:
            tokenizer = make_test_tokenizer()
        ns = cfg.namespace
        kv_pub = KvEventPublisher(runtime, ns, args.component,
                                  runtime.instance_id)
        metrics_pub = WorkerMetricsPublisher(runtime, ns, args.component,
                                             runtime.instance_id)
        inventory_pub = KvInventoryPublisher(runtime, ns, args.component,
                                             runtime.instance_id)
        def build_engine() -> TPUEngine:
            params = None
            if ckpt is not None:
                from dynamo_tpu.engine.weights import load_hf_weights
                params = load_hf_weights(engine_cfg.model, ckpt)
            return TPUEngine(engine_cfg, params=params, kv_publisher=kv_pub,
                             metrics_publisher=metrics_pub,
                             metrics_registry=runtime.metrics.namespace(ns)
                             .component(args.component))

        mh_group = (args.mh_group
                    or f"eng-{engine_cfg.model.name}").replace("/", "-")
        if multihost_engine and args.node_rank > 0:
            # SPMD follower: replay the leader's dispatch stream on this
            # host's shard of the global mesh. No registration, no HTTP.
            from dynamo_tpu.engine import multihost
            params = None
            if ckpt is not None:
                from dynamo_tpu.engine.weights import load_hf_weights
                params = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: load_hf_weights(engine_cfg.model, ckpt))
            print(f"TPU_FOLLOWER_READY rank={args.node_rank}", flush=True)
            await multihost.run_follower(
                engine_cfg, runtime.require_coordinator(), mh_group,
                args.node_rank, params=params)
            return

        if args.num_nodes > 1 and not multihost_engine:
            # Multi-node worker GROUP: each host runs its own single-host
            # mesh (a dp-style replica set) and the leader/worker barrier
            # coordinates bring-up — every host must agree on the model +
            # mesh shape before any of them starts serving (reference
            # multi-node bootstrap, leader_worker_barrier.rs). For a
            # SINGLE engine spanning hosts, set JAX_COORDINATOR_ADDRESS:
            # rank 0 serves through engine/multihost.LeaderRunner and the
            # other ranks replay its dispatch stream (handled above).
            from dynamo_tpu.runtime.barrier import (LeaderBarrier,
                                                    WorkerBarrier)
            client = runtime.require_coordinator()
            bid = f"engine-{model_name}"
            shape = {"model": model_name, "tp": args.tp, "pp": args.pp,
                     "sp": args.sp, "dp": args.dp}
            if args.node_rank == 0:
                peers = await LeaderBarrier(
                    client, bid, args.num_nodes - 1).sync(shape)
                log.info("multi-node group assembled: leader + %d peers",
                         len(peers))
            else:
                leader = await WorkerBarrier(
                    client, bid, str(args.node_rank)).sync(shape)
                if leader != shape:
                    raise SystemExit(
                        f"node {args.node_rank} config {shape} does not "
                        f"match leader {leader}")
        loras = _lora_args(args)
        if multihost_engine and loras:
            raise SystemExit(
                "--lora is not supported with a multi-host single engine "
                "yet: adapter hot-loads are not in the follower replay "
                "stream (engine/multihost.py)")
        # Engine construction blocks for seconds (weight load + sharded
        # device_put + first compiles); run it off the event loop so the
        # coordinator lease keepalives keep flowing.
        engine = await asyncio.get_running_loop().run_in_executor(
            None, build_engine)
        if loras:
            # Host-side parse/pad/stack only (device uploads happen
            # lazily on the engine thread at first use): off the loop so
            # large checkpoints don't stall lease keepalives.
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: [engine.register_adapter(n, path=p)
                               for n, p in loras])
        if multihost_engine:
            # Leader: publish every device call to the follower replay
            # stream, and hold serving until every follower is listening.
            from dynamo_tpu.engine import multihost
            engine.runner = multihost.LeaderRunner(
                engine.runner, runtime.require_coordinator(),
                asyncio.get_running_loop(), mh_group)
            await multihost.leader_barrier(
                runtime.require_coordinator(), mh_group, args.num_nodes - 1,
                {"model": engine_cfg.model.name,
                 "mesh": [args.dp, args.pp, args.sp, args.tp],
                 # Followers adopt the leader's ACTUAL pool size so
                 # auto-sizing can never diverge across hosts.
                 "num_pages": engine.runner.num_pages})
            log.info("multihost leader: %d followers in lockstep",
                     args.num_nodes - 1)
        from dynamo_tpu.llm.disagg import PREFILL_COMPONENT
        prefill_component = args.prefill_component or PREFILL_COMPONENT
        # Direct KV data plane (the NIXL role): every worker runs the
        # server side — prefill workers stage parcels on it, and any
        # worker with host tiers serves G4 remote-tier block fetches.
        plane = None
        peer_watch_task = None
        if not args.no_kv_plane:
            from dynamo_tpu.llm.kv_plane import (KvPlaneServer,
                                                 RemoteBlockSource)
            plane = KvPlaneServer(
                host=args.kv_plane_host,
                block_provider=(engine.host_cache.get
                                if engine.host_cache is not None else None))
            plane.start()
            engine.plane = plane  # /debug/kv + dynamo_tpu_kv_plane_* stats
            coordinator = runtime.require_coordinator()
            await coordinator.kv_put(
                f"kvplane/{cfg.namespace}/{runtime.instance_id:x}",
                {"addr": plane.address, "model": model_name},
                lease_id=coordinator.primary_lease_id)
            # G4 remote tier: watch peer plane registrations so prefix
            # extensions can onboard blocks a PEER's host tier holds
            # instead of recomputing (engine._try_onboard). Short-timeout
            # client: the consult runs on the engine thread.
            engine.remote_source = RemoteBlockSource(self_addr=plane.address)
            peer_watch = await coordinator.watch_prefix(
                f"kvplane/{cfg.namespace}/")
            peers: dict[str, str] = {
                item["k"]: item["v"]["addr"]
                for item in peer_watch.snapshot
                if item["v"].get("model") == model_name}
            engine.remote_source.peers = [a for a in peers.values()
                                          if a != plane.address]

            async def watch_peers() -> None:
                # Must not die silently: a frozen peer list both misses
                # new workers and keeps feeding dead addresses to the G4
                # consult. On watch failure, log and re-establish.
                watch = peer_watch
                while True:
                    try:
                        async for event in watch:
                            if event["event"] == "put" and \
                                    event["value"].get("model") == model_name:
                                peers[event["key"]] = event["value"]["addr"]
                            elif event["event"] == "delete":
                                gone = peers.pop(event["key"], None)
                                if gone is not None:
                                    # worker_leave/scale-in: drop the
                                    # peer AND its breaker state now,
                                    # not at staleness TTL.
                                    engine.remote_source.drop_peer(gone)
                            engine.remote_source.peers = [
                                a for a in peers.values()
                                if a != plane.address]
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — log and re-watch
                        log.exception("kvplane peer watch failed; retrying")
                    await asyncio.sleep(2.0)
                    try:
                        watch = await coordinator.watch_prefix(
                            f"kvplane/{cfg.namespace}/")
                        peers.clear()
                        peers.update({
                            item["k"]: item["v"]["addr"]
                            for item in watch.snapshot
                            if item["v"].get("model") == model_name})
                        engine.remote_source.peers = [
                            a for a in peers.values() if a != plane.address]
                    except (ConnectionError, OSError):
                        log.warning("kvplane peer re-watch failed; will "
                                    "retry")

            peer_watch_task = asyncio.create_task(watch_peers())
        if args.prefill_dispatch == "queue" and args.no_kv_plane:
            raise SystemExit(
                "--prefill-dispatch queue needs the KV data plane "
                "(queue replies carry plane tickets); drop "
                "--no-kv-plane or use --prefill-dispatch direct")
        from dynamo_tpu.llm.reconfig import RoleManager
        from dynamo_tpu.llm.standby import ScaleAgent
        roles = RoleManager(
            runtime,
            make_profile_builder(runtime, args, engine, engine_cfg,
                                 tokenizer, model_name, plane,
                                 prefill_component),
            role=args.mode,
            status_extra={"backend": "tpu", "model": model_name})
        # Autoscaling (llm/standby.py): every worker answers scale
        # directives (retire drains it out); --standby parks it warm
        # and deregistered until the planner promotes it. The engine is
        # already built — weights loaded, warmup done — so the promote
        # pays only registration, not cold start.
        scale_agent = ScaleAgent(
            runtime, roles, standby=args.standby,
            status_extra={"backend": "tpu", "model": model_name},
            metrics=runtime.metrics)
        if not args.standby:
            await roles.start()
        # Fleet inventory digests (KV & capacity plane): published from
        # the engine loop alongside KV events + ForwardPassMetrics, with
        # a periodic republish so an idle worker still shows up.
        engine.inventory_publisher = inventory_pub
        engine.start()
        inventory_pub.start_periodic(engine.inventory_digest)
        # Observability plane (docs/OBSERVABILITY.md): flight-recorder
        # bundle context for THIS worker, and the per-worker system
        # status server (DTPU_SYSTEM_ENABLED=1) serving /metrics +
        # /debug/{traces,slo,requests,flight,kv} next to the engine.
        import dataclasses as _dc

        from dynamo_tpu.runtime import flight as _flight
        from dynamo_tpu.runtime import journal as _journal
        from dynamo_tpu.runtime import slo as _slo
        _flight.configure(metrics=runtime.metrics,
                          config_fingerprint=_dc.asdict(cfg))
        _slo.configure(cfg.slo, metrics=runtime.metrics).on_page(
            _flight.on_slo_page)
        # Decision plane (runtime/journal.py): this worker's preempts,
        # role-flip edges, and chaos injections ride the event plane
        # into the frontend's merged /debug/timeline.
        _journal.configure(worker=f"{runtime.instance_id:x}",
                           metrics=runtime.metrics)
        journal_pub = _journal.JournalPublisher(
            runtime.require_coordinator(), cfg.namespace,
            f"{runtime.instance_id:x}")
        journal_pub.start_periodic()
        # After journal.configure: the standby_ready event must carry
        # this worker's id, not the "proc" placeholder.
        await scale_agent.start()
        status_server = None
        if cfg.system_enabled:
            from dynamo_tpu.llm.fleet import register_status_server
            from dynamo_tpu.runtime.health import SystemStatusServer
            status_server = SystemStatusServer(runtime, host=cfg.bind_host,
                                               port=cfg.system_port,
                                               role_manager=roles,
                                               kv_provider=engine.kv_status,
                                               perf_provider=engine.perf_status,
                                               scale_agent=scale_agent)
            await status_server.start()
            # Advertise for the frontend's /debug/fleet fan-out
            # (lease-bound: the entry dies with this worker).
            await register_status_server(
                runtime, status_server.port,
                extra={"backend": "tpu", "component": args.component,
                       "model": model_name})
        port = (roles.profile.servers[0].port
                if roles.profile and roles.profile.servers else 0)
        mode = "standby" if args.standby else args.mode
        print(f"TPU_WORKER_READY mode={mode} port={port} "
              f"worker={runtime.instance_id:x} pages={engine.runner.num_pages}",
              flush=True)
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        await runtime.wait_for_shutdown()
        journal_pub.stop_periodic()
        inventory_pub.stop_periodic()
        engine.stop()
        if multihost_engine:
            # Engine loop is drained — no more dispatches can race this.
            from dynamo_tpu.engine import multihost
            try:
                # Surface a transport failure on the LAST dispatch (acks
                # are pipelined one behind) before declaring clean stop.
                pending = engine.runner.pending_ack()
                if pending is not None:
                    await asyncio.wrap_future(pending)
                await runtime.require_coordinator().publish(
                    multihost.DISPATCH_SUBJECT.format(group=mh_group),
                    {"m": "stop"})
            except (ConnectionError, OSError):
                # Coordinator already gone (whole-deployment teardown);
                # followers exit with it.
                pass
        # The role manager owns the serving profile: endpoint servers and
        # role-specific machinery (queue workers, disagg clients/watches)
        # all tear down through it, whatever role we ended up in.
        await scale_agent.stop()
        await roles.stop()
        if status_server is not None:
            await status_server.stop()
        if peer_watch_task is not None:
            peer_watch_task.cancel()
        if plane is not None:
            if engine.remote_source is not None:
                engine.remote_source.client.close()
            plane.close()
    finally:
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
