"""Worker backends (capability parity with reference components/backends/*):
echo (pipeline smoke), mocker (TPU-timing simulator), tpu (the real JAX engine).
"""
