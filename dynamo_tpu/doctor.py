"""Deployment doctor: one command that says what's broken.

Reference: ``deploy/dynamo_check.py`` — a diagnostic script that probes the
environment (imports, GPU, etcd/NATS connectivity, registered workers) and
prints OK/WARN/FAIL per check. The TPU-native equivalent probes:

- interpreter + required libraries
- accelerator devices visible to JAX (without forcing a compile)
- the native extension toolchain (C++ radix index builds/loads)
- coordinator connectivity + KV/queue/pub-sub round-trips + latency
- registered models and live endpoint instances (with TCP reachability)
- disaggregation roles: each worker's current role / drain state / last
  flip outcome from the role status plane (llm/reconfig.py), WARNing on
  workers stuck mid-transition or a fleet with zero prefill-capable
  workers
- an HTTP frontend, when given (``/health``, ``/v1/models``)
- the observability plane on that frontend: ``/metrics`` exposition
  (FAIL when unreachable), ``/debug/slo`` (WARN when no SLO targets are
  configured), ``/debug/flight``, and tracing (WARN when disabled)
- the KV & capacity pane: registered worker status servers on the
  coordinator, ``/debug/fleet`` (WARN on partial results — some workers
  unreachable — or an empty fleet), and the KV router's decision
  telemetry (cache-aware rate / regret) when KV routing is on
- the engine perf plane: ``/debug/perf`` (+ the fleet pane's per-worker
  perf views), WARNing on unexpected steady-state recompiles, HBM
  headroom under 10%, or live roofline_frac regressing > 20% below the
  recorded expectation (DTPU_EXPECTED_ROOFLINE_FRAC / model card)
- the decision plane: ``/debug/timeline`` (runtime/journal.py), WARNing
  on journal-ring overflow drops, breakers that flapped open more than
  N times in the window, and live canary failure streaks

Exit code 0 = no FAIL. Run: ``python -m dynamo_tpu.doctor
[--coordinator-url tcp://...] [--frontend-url http://...]``.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

OK, WARN, FAIL, SKIP = "OK  ", "WARN", "FAIL", "skip"


class Report:
    def __init__(self):
        self.rows: list[tuple[str, str, str]] = []

    def add(self, status: str, check: str, detail: str = "") -> None:
        self.rows.append((status, check, detail))
        print(f"[{status}] {check}" + (f" — {detail}" if detail else ""),
              flush=True)

    @property
    def failed(self) -> bool:
        return any(s == FAIL for s, _, _ in self.rows)


def check_imports(rep: Report) -> None:
    rep.add(OK, "python", sys.version.split()[0])
    # grpc/transformers are optional extras (gRPC frontend, HF
    # checkpoints): a core aggregated-serving node is healthy without
    # them, so missing ones WARN rather than FAIL.
    for mod, required in (("jax", True), ("numpy", True),
                          ("msgpack", True), ("aiohttp", True),
                          ("grpc", False), ("transformers", False)):
        try:
            m = __import__(mod)
            rep.add(OK, f"import {mod}", getattr(m, "__version__", ""))
        except ImportError as exc:
            rep.add(FAIL if required else WARN, f"import {mod}",
                    str(exc) if required else "optional; not installed")


def check_devices(rep: Report) -> None:
    try:
        import jax
        devs = jax.devices()
        plat = devs[0].platform if devs else "none"
        status = OK if plat in ("tpu", "axon") else WARN
        rep.add(status, "jax devices",
                f"{len(devs)}x {plat} ({devs[0].device_kind})" if devs
                else "no devices")
    except Exception as exc:  # noqa: BLE001 — any backend-init failure
        rep.add(FAIL, "jax devices", str(exc)[:200])


def check_native(rep: Report) -> None:
    try:
        from dynamo_tpu.llm.kv_router.protocols import (KvCacheEvent,
                                                        RouterEvent)
        from dynamo_tpu.native import radix
        if radix.available:
            t = radix.NativeRadixTree()
            t.apply_event(RouterEvent(worker_id=1,
                                      event=KvCacheEvent.stored([11, 12])))
            assert t.find_matches([11, 12]).get(1) == 2
            rep.add(OK, "native radix (C++)", "built + loaded + sane")
        else:
            rep.add(WARN, "native radix (C++)",
                    "unavailable; Python fallback in use (g++ missing?)")
    except Exception as exc:  # noqa: BLE001
        rep.add(FAIL, "native radix (C++)", str(exc)[:200])


async def check_coordinator(rep: Report, url: str) -> None:
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.coordinator_client import CoordinatorClient
    try:
        host, port = RuntimeConfig(coordinator_url=url).coordinator_addr
    except ValueError:
        rep.add(FAIL, "coordinator connect",
                f"{url}: expected tcp://host:port")
        return
    t0 = time.monotonic()
    try:
        client = await asyncio.wait_for(
            CoordinatorClient.connect(host, port), timeout=5)
    except (OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "coordinator connect", f"{url}: {exc}")
        return
    rep.add(OK, "coordinator connect",
            f"{url} in {1e3 * (time.monotonic() - t0):.1f} ms")
    try:
        key = f"doctor/{id(client):x}"
        t0 = time.monotonic()
        await client.kv_put(key, {"t": time.time()})
        assert (await client.kv_get(key)) is not None
        await client.kv_delete(key)
        rep.add(OK, "coordinator KV round-trip",
                f"{1e3 * (time.monotonic() - t0):.1f} ms")

        sub = await client.subscribe("doctor.ping")
        await client.publish("doctor.ping", {"n": 1})
        try:
            await asyncio.wait_for(sub.messages.get(), timeout=2)
            rep.add(OK, "coordinator pub/sub", "")
        except asyncio.TimeoutError:
            rep.add(FAIL, "coordinator pub/sub", "published event not seen")
        await sub.cancel()

        q = f"doctor-q-{id(client):x}"
        await client.queue_push(q, {"n": 1})
        got = await client.queue_pop(q, timeout=2)
        rep.add(OK if got else FAIL, "coordinator queue",
                "" if got else "pushed item not popped")

        models = await client.kv_get_prefix("models/")
        names = sorted({m["v"].get("model_name", "?") for m in models})
        rep.add(OK if models else WARN, "registered models",
                ", ".join(names) if names else "none registered")
        check_adapter_cards(rep, [m["v"] for m in models])

        instances = await client.kv_get_prefix("instances/")
        rep.add(OK if instances else WARN, "live instances",
                f"{len(instances)} registered" if instances else "none")
        for item in instances:
            v = item["v"]
            where = f"{v.get('host')}:{v.get('port')}"
            path = item["k"].split("instances/", 1)[-1]
            try:
                _, w = await asyncio.wait_for(
                    asyncio.open_connection(v.get("host"), v.get("port")),
                    timeout=2)
                w.close()
                rep.add(OK, f"instance {path}", f"tcp {where} reachable")
            except (OSError, asyncio.TimeoutError) as exc:
                rep.add(FAIL, f"instance {path}", f"tcp {where}: {exc}")

        disagg = await client.kv_get_prefix("disagg/")
        if disagg:
            rep.add(OK, "disagg config",
                    "; ".join(f"{d['k']}={d['v']}" for d in disagg))
        check_roles(rep, await client.kv_get_prefix("rolestatus/"))
        check_autoscale(
            rep,
            [it["v"] for it in await client.kv_get_prefix("standby/")
             if isinstance(it.get("v"), dict)],
            [{"key": it["k"], **it["v"]}
             for it in await client.kv_get_prefix("scale/")
             if isinstance(it.get("v"), dict)])
        system = await client.kv_get_prefix("system/")
        if system:
            rep.add(OK, "status servers",
                    f"{len(system)} registered for the fleet pane "
                    "(/debug/fleet)")
        else:
            rep.add(WARN, "status servers",
                    "none registered: /debug/fleet will be empty (set "
                    "DTPU_SYSTEM_ENABLED=1 on workers)")
    except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
        # Coordinator died mid-check: report it, keep the doctor alive so
        # later checks (frontend) still run.
        rep.add(FAIL, "coordinator", f"lost mid-check: {exc}")
    finally:
        await client.close()


#: A worker reporting draining/flipping longer than this is stuck — the
#: drain window (retire_drain_s default 30s) is far smaller.
ROLE_STUCK_S = 120.0


def check_roles(rep: Report, items: list[dict]) -> None:
    """Disaggregation role report (llm/reconfig.py fleet view): each
    worker's current role, drain state, and last flip outcome; WARN on a
    fleet stuck mid-transition or with zero prefill-capable workers."""
    statuses = [it["v"] for it in items if isinstance(it.get("v"), dict)]
    if not statuses:
        return  # fixed-role deployment: nothing to report
    now = time.time()
    stuck, failed = [], []
    for s in statuses:
        role, state = s.get("role", "?"), s.get("state", "?")
        detail = f"role={role} state={state} epoch={s.get('epoch', 0)}"
        last = s.get("last_outcome") or {}
        if last:
            detail += (f" last_flip={last.get('from')}->{last.get('to')}"
                       f":{last.get('outcome')}")
        age = now - float(s.get("ts") or now)
        if state in ("draining", "flipping") and age > ROLE_STUCK_S:
            stuck.append(s)
            rep.add(WARN, f"worker role {s.get('worker', '?')}",
                    f"{detail} — stuck {state} for {age:.0f}s")
            continue
        if last.get("outcome") not in (None, "ok", "noop", "duplicate"):
            failed.append(s)
            rep.add(WARN, f"worker role {s.get('worker', '?')}",
                    f"{detail} — last flip did not converge cleanly")
            continue
        rep.add(OK, f"worker role {s.get('worker', '?')}", detail)
    prefill_capable = sum(1 for s in statuses
                          if s.get("role") in ("prefill", "agg")
                          and s.get("state") == "serving")
    decode_capable = sum(1 for s in statuses
                         if s.get("role") in ("decode", "agg")
                         and s.get("state") == "serving")
    if prefill_capable == 0:
        rep.add(WARN, "role fleet", "zero prefill-capable workers serving: "
                "remote prefill degrades to local everywhere")
    elif decode_capable == 0:
        rep.add(WARN, "role fleet", "zero decode-capable workers serving: "
                "no registered model endpoint can answer")
    else:
        rep.add(OK, "role fleet",
                f"{prefill_capable} prefill-capable / {decode_capable} "
                f"decode-capable of {len(statuses)} workers")


async def check_frontend(rep: Report, url: str) -> None:
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/health",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                rep.add(OK if r.status == 200 else FAIL, "frontend /health",
                        f"{r.status}")
            async with session.get(f"{url}/v1/models",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                body = await r.json()
                names = [m.get("id") for m in body.get("data", [])]
                rep.add(OK if r.status == 200 else FAIL,
                        "frontend /v1/models",
                        ", ".join(names) if names else "no models")
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "frontend", f"{url}: {exc}")


async def check_observability(rep: Report, url: str) -> None:
    """Probe the decision-grade observability surface on a frontend (or
    a worker status server): metrics exposition, the SLO plane, and the
    flight recorder. docs/OBSERVABILITY.md documents every endpoint."""
    import os

    import aiohttp
    url = url.rstrip("/")
    if os.environ.get("DTPU_TRACING", "1").strip().lower() in (
            "0", "false", "no", "off"):
        rep.add(WARN, "tracing env", "DTPU_TRACING=0: spans disabled in "
                "processes launched from this environment")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/metrics",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                body = await r.text()
                series = sum(1 for line in body.splitlines()
                             if line.startswith("dynamo_tpu_"))
                rep.add(OK if r.status == 200 and series else FAIL,
                        "metrics exposition",
                        f"{series} dynamo_tpu_* sample lines"
                        if r.status == 200 else f"HTTP {r.status}")
            async with session.get(f"{url}/debug/slo",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/slo", f"HTTP {r.status}")
                else:
                    slo = await r.json()
                    targets = sorted(slo.get("targets") or {})
                    if not slo.get("enabled") or not targets:
                        rep.add(WARN, "/debug/slo",
                                "no SLO targets configured (set "
                                "DTPU_SLO_TTFT_P99_MS etc. or the [slo] "
                                "TOML table): burn-rate alerting is off")
                    else:
                        level = (slo.get("pressure") or {}).get("level", 0)
                        rep.add(OK, "/debug/slo",
                                f"targets: {', '.join(targets)}; "
                                f"pressure level {level}")
            async with session.get(f"{url}/debug/flight",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/flight", f"HTTP {r.status}")
                else:
                    fl = await r.json()
                    meta = fl.get("meta") or {}
                    rep.add(OK if meta.get("enabled") else WARN,
                            "/debug/flight",
                            f"{meta.get('records', 0)} windows recorded"
                            if meta.get("enabled")
                            else "flight recorder disabled "
                            "(DTPU_FLIGHT_CAPACITY=0)")
            async with session.get(f"{url}/control/role",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status == 200:
                    role = await r.json()
                    rep.add(OK, "/control/role",
                            f"role={role.get('role')} "
                            f"state={role.get('state')} "
                            f"epoch={role.get('epoch')}")
                # 404 = a frontend or a fixed-role worker: not an error.
            async with session.get(f"{url}/debug/traces/recent",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/traces", f"HTTP {r.status}")
                else:
                    idx = await r.json()
                    rep.add(OK if idx.get("enabled") else WARN,
                            "/debug/traces",
                            f"{len(idx.get('traces') or [])} recent traces"
                            if idx.get("enabled")
                            else "tracing disabled (DTPU_TRACING=0) on "
                            "the probed process")
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "observability", f"{url}: {exc}")


async def check_fleet_kv(rep: Report, url: str) -> None:
    """KV & capacity pane (docs/OBSERVABILITY.md "KV & capacity"): the
    frontend's /debug/fleet merged per-worker view. WARNs on partial
    results (some workers unreachable) and on a fleet with zero
    reachable status servers; FAILs only when the pane itself is
    broken."""
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/debug/fleet",
                                   timeout=aiohttp.ClientTimeout(15)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/fleet", f"HTTP {r.status}")
                    return
                fleet = await r.json()
            workers = fleet.get("workers") or {}
            agg = fleet.get("aggregate") or {}
            if not workers:
                rep.add(WARN, "/debug/fleet",
                        "no worker status servers registered "
                        "(DTPU_SYSTEM_ENABLED=1 enables the pane)")
            elif fleet.get("partial"):
                down = [w for w, res in workers.items()
                        if not res.get("ok")]
                rep.add(WARN, "/debug/fleet",
                        f"{agg.get('workers_ok', 0)}/{len(workers)} "
                        f"workers reachable; down: {', '.join(down)}")
            else:
                rep.add(OK, "/debug/fleet",
                        f"{agg.get('workers_ok', 0)} workers, occupancy "
                        f"{agg.get('occupancy', 0.0):.2f}, "
                        f"{agg.get('cached_blocks', 0)} cached blocks, "
                        f"hit rate {agg.get('hit_rate', 0.0):.2f}")
            router = ((fleet.get("router") or {}).get("routers") or {})
            for model, view in router.items():
                dec = view.get("decisions") or {}
                if dec.get("decisions"):
                    rate = dec.get("cache_aware_rate")
                    rep.add(OK, f"kv routing {model}",
                            f"{dec['decisions']} decisions, "
                            f"cache-aware {rate:.2f}, regret p99 "
                            f"{dec.get('regret_p99')}")
            async with session.get(f"{url}/debug/kv",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                # 404 = a round_robin/random frontend with no provider:
                # not an error, just no KV-aware routing to report.
                if r.status not in (200, 404):
                    rep.add(FAIL, "/debug/kv", f"HTTP {r.status}")
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "fleet kv pane", f"{url}: {exc}")


#: Adapter-miss storm threshold (check_adapters): WARN when more than
#: this fraction of adapter requests forced a hot-load — the resident
#: slot count is too small for the working set (raise --max-adapters or
#: pin the hot tenants).
ADAPTER_MISS_WARN_RATE = 0.3
ADAPTER_MISS_MIN_REQUESTS = 20


def check_adapter_cards(rep: Report, entries: list[dict]) -> None:
    """Model-card sanity for LoRA adapters: every adapter card's
    ``lora_base`` must name a model some worker still serves — a
    dangling binding means requests for the adapter name will route to
    a worker that 404s them (the base worker role-flipped or retired
    without its adapter cards)."""
    names = {e.get("model_name") for e in entries}
    adapters = []
    for e in entries:
        extra = (((e.get("card") or {}).get("runtime_config") or {})
                 .get("extra") or {})
        base = extra.get("lora_base")
        if not base:
            continue
        adapters.append((e.get("model_name"), base))
        if base not in names:
            rep.add(WARN, f"adapter card {e.get('model_name')}",
                    f"points at base model {base!r} which no registered "
                    f"worker serves (stale card after a role flip / "
                    f"scale-in?)")
    if adapters:
        bases = sorted({b for _, b in adapters})
        rep.add(OK, "adapter cards",
                f"{len(adapters)} adapter name(s) over base "
                f"{', '.join(bases)}")


def check_adapter_workers(rep: Report, workers: dict) -> None:
    """Per-worker AdapterStore health from the /debug/fleet pane:
    resident/registered counts, eviction totals, and the adapter-miss
    storm WARN (hot-load rate above threshold — every miss pays a
    device upload before the request can prefill)."""
    seen = False
    for worker, res in sorted(workers.items()):
        ad = (res.get("kv") or {}).get("adapters") if res.get("ok") else None
        if not ad:
            continue
        seen = True
        requests = sum((ad.get("requests_total") or {}).values())
        miss = ad.get("miss_total", 0)
        detail = (f"{len(ad.get('resident') or {})}/"
                  f"{ad.get('max_adapters')} resident, "
                  f"{len(ad.get('registered') or [])} registered, "
                  f"loads {ad.get('loads_total', 0)}, evictions "
                  f"{ad.get('evictions_total', 0)}, misses {miss}/"
                  f"{requests} req")
        if (requests >= ADAPTER_MISS_MIN_REQUESTS
                and miss > ADAPTER_MISS_WARN_RATE * requests):
            rep.add(WARN, f"adapters {worker}",
                    detail + " — adapter-miss storm: the resident slot "
                    "count is below the working set (raise "
                    "--max-adapters or pin hot tenants)")
        else:
            rep.add(OK, f"adapters {worker}", detail)
    if not seen:
        rep.add(SKIP, "adapters", "no worker reports an adapter store")


async def check_adapters(rep: Report, url: str) -> None:
    """LoRA adapter serving (docs/OBSERVABILITY.md "Adapters"): reads
    the frontend's /debug/fleet pane for per-worker adapter stores."""
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/debug/fleet",
                                   timeout=aiohttp.ClientTimeout(15)) as r:
                if r.status != 200:
                    rep.add(SKIP, "adapters", f"/debug/fleet HTTP {r.status}")
                    return
                fleet = await r.json()
        check_adapter_workers(rep, fleet.get("workers") or {})
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(SKIP, "adapters", f"{url}: {exc}")


async def check_kv_federation(rep: Report, url: str) -> None:
    """KV federation (docs/OBSERVABILITY.md "KV federation"): is the
    router scoring with inventory overlap, and is the tier/peer plane
    healthy? WARNs when federation is off, when peer breakers are
    open, and when the tier walk keeps falling back to recompute."""
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/debug/kv",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status == 404:
                    rep.add(WARN, "kv federation",
                            "frontend has no KV pane (round_robin/random "
                            "router): federated routing inactive")
                    return
                if r.status != 200:
                    rep.add(FAIL, "kv federation", f"HTTP {r.status}")
                    return
                body = await r.json()
            for model, view in (body.get("routers") or {}).items():
                if view.get("federation") is False:
                    rep.add(WARN, f"federation {model}",
                            "inventory-overlap scoring DISABLED "
                            "(--no-kv-federation): prefixes cached in "
                            "peer tiers recompute locally")
                else:
                    fleet_view = view.get("fleet") or {}
                    totals = fleet_view.get("totals") or {}
                    rep.add(OK, f"federation {model}",
                            f"{totals.get('workers', 0)} inventories, "
                            f"{totals.get('blocks', 0)} fleet blocks, "
                            f"{totals.get('stale', 0)} stale digests")
            async with session.get(f"{url}/debug/fleet",
                                   timeout=aiohttp.ClientTimeout(15)) as r:
                if r.status != 200:
                    return
                fleet = await r.json()
            for worker, res in (fleet.get("workers") or {}).items():
                kv = res.get("kv") if res.get("ok") else None
                if not isinstance(kv, dict):
                    continue
                kvbm = kv.get("kvbm") or {}
                remote = kv.get("remote") or {}
                open_breakers = remote.get("breakers_open", 0)
                if open_breakers:
                    rep.add(WARN, f"peer tier {worker}",
                            f"{open_breakers} peer breaker(s) open "
                            f"({remote.get('fetch_failures', 0)} pull "
                            "failures): cross-worker reuse degraded")
                fallbacks = kvbm.get("recompute_fallbacks", 0)
                promotions = kvbm.get("promotions", 0)
                if fallbacks > max(10, 3 * max(1, promotions)):
                    rep.add(WARN, f"kvbm {worker}",
                            f"{fallbacks} tier-walk recompute fallbacks "
                            f"vs {promotions} promotions: the ladder "
                            "rarely holds what requests need (budget or "
                            "watermark tuning?)")
                elif kvbm:
                    rep.add(OK, f"kvbm {worker}",
                            f"{kvbm.get('watermark_demotions', 0)} "
                            "watermark demotions, "
                            f"{promotions} promotions, "
                            f"{kvbm.get('peer_pull_blocks', 0)} peer "
                            "blocks pulled")
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "kv federation", f"{url}: {exc}")


def _perf_views(body: dict, fleet: dict | None) -> list[tuple[str, dict]]:
    """Flatten one /debug/perf body (+ optional /debug/fleet per-worker
    perf views) into named engine-grade views to judge."""
    views = [(str(body.get("role") or "process"), body)]
    for name, eng in (body.get("engines") or {}).items():
        views.append((f"engine {name}", eng))
    for worker, res in ((fleet or {}).get("workers") or {}).items():
        perf = res.get("perf")
        if isinstance(perf, dict) and "compiles" in perf:
            views.append((f"worker {worker}", perf))
    return views


#: HBM headroom below this fraction of bytes_limit is a WARN: the next
#: long context or shape bucket will OOM-preempt instead of serving.
PERF_HBM_HEADROOM = 0.10
#: Live roofline_frac more than this fraction BELOW the model-card /
#: config expectation is a WARN (ISSUE: "regressing > 20%").
PERF_ROOFLINE_REGRESSION = 0.20


async def check_perf(rep: Report, url: str) -> None:
    """Engine perf plane (docs/OBSERVABILITY.md "Engine perf plane"):
    probe /debug/perf (+ the fleet pane's per-worker perf views) and
    WARN on any unexpected steady-state recompile, HBM headroom below
    10%, or live roofline_frac regressing more than 20% below the
    recorded expectation."""
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/debug/perf",
                                   timeout=aiohttp.ClientTimeout(5)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/perf", f"HTTP {r.status}")
                    return
                body = await r.json()
            fleet = None
            try:
                async with session.get(
                        f"{url}/debug/fleet",
                        timeout=aiohttp.ClientTimeout(15)) as r:
                    if r.status == 200:
                        fleet = await r.json()
            except (aiohttp.ClientError, OSError,
                    asyncio.TimeoutError):
                fleet = None  # pane probed separately; perf view optional
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "perf plane", f"{url}: {exc}")
        return
    for name, view in _perf_views(body, fleet):
        compiles = view.get("compiles") or {}
        programs = compiles.get("programs") or {}
        unexpected = compiles.get("unexpected_recompiles_total", 0)
        if unexpected:
            rep.add(WARN, f"perf {name}",
                    f"{unexpected} unexpected steady-state recompile(s) — "
                    "a served shape is recompiling on the hot path (see "
                    "perf.recompile spans)")
        elif programs:
            total_s = compiles.get("compile_seconds_total", 0.0)
            rep.add(OK, f"perf {name}",
                    f"{compiles.get('compiles_total', 0)} compiles over "
                    f"{len(programs)} programs ({total_s:.1f}s), zero "
                    "unexpected recompiles")
        hbm = view.get("hbm") or {}
        limit = hbm.get("bytes_limit") or 0
        if limit:
            headroom = 1.0 - hbm.get("bytes_in_use", 0) / limit
            if headroom < PERF_HBM_HEADROOM:
                rep.add(WARN, f"perf {name} HBM",
                        f"headroom {headroom:.1%} < "
                        f"{PERF_HBM_HEADROOM:.0%} of "
                        f"{limit / (1 << 30):.1f} GiB: next shape bucket "
                        "or long context will thrash the KV pool")
            else:
                rep.add(OK, f"perf {name} HBM",
                        f"{hbm.get('bytes_in_use', 0) / (1 << 30):.2f} / "
                        f"{limit / (1 << 30):.1f} GiB in use "
                        f"(headroom {headroom:.0%})")
        roofline = view.get("roofline") or {}
        frac = roofline.get("frac")
        expected = roofline.get("expected_frac")
        if expected and frac is not None:
            floor = expected * (1.0 - PERF_ROOFLINE_REGRESSION)
            if frac < floor:
                rep.add(WARN, f"perf {name} roofline",
                        f"live roofline_frac {frac:.3f} regressed below "
                        f"{floor:.3f} ({PERF_ROOFLINE_REGRESSION:.0%} "
                        f"under the recorded expectation {expected})")
            else:
                rep.add(OK, f"perf {name} roofline",
                        f"{frac:.3f} vs expected {expected} (ok)")


#: Breaker open-transitions per worker in the timeline window above
#: which the doctor calls it flapping (open -> half-open -> open churn:
#: the worker is sick but keeps winning its half-open probe).
BREAKER_FLAP_N = 3
#: Consecutive canary failures on one worker worth a WARN.
CANARY_FAIL_N = 3


def check_decision_plane(rep: Report, timeline: dict) -> None:
    """Decision plane (docs/OBSERVABILITY.md "Decision plane"): judge a
    /debug/timeline body — journal-ring overflow drops, repeated canary
    failures, breaker flapping. Pure function over the payload so the
    checks are unit-testable without HTTP."""
    events = timeline.get("events") or []
    local = timeline.get("local") or {}
    dropped = int(local.get("dropped_overflow") or 0)
    gaps = int(timeline.get("gaps") or 0)
    if dropped or gaps:
        rep.add(WARN, "journal ring",
                f"{dropped} events dropped to ring overflow, {gaps} "
                "timeline gaps (raise DTPU_JOURNAL_CAPACITY or the "
                "publisher cadence): cause chains may be broken")
    else:
        rep.add(OK, "journal ring",
                f"{len(events)} events merged, zero overflow drops")
    # Breaker flaps: open transitions per worker in the window.
    opens: dict[str, int] = {}
    for e in events:
        attrs = e.get("attrs") or {}
        if (e.get("kind") == "breaker_transition"
                and attrs.get("to") == "open"):
            w = str(attrs.get("worker_id") or "?")
            opens[w] = opens.get(w, 0) + 1
    for w, n in sorted(opens.items()):
        if n > BREAKER_FLAP_N:
            rep.add(WARN, f"breaker {w}",
                    f"flapped open {n} times in the timeline window "
                    "(open -> half-open -> open churn): probes keep "
                    "re-admitting a sick worker")
    if opens and all(n <= BREAKER_FLAP_N for n in opens.values()):
        rep.add(OK, "breakers",
                f"{sum(opens.values())} open transition(s) across "
                f"{len(opens)} worker(s), none flapping")
    # Canary: trailing consecutive failures per worker (a fail streak
    # ended by canary_ok is a recovered incident, not a live one).
    streaks: dict[str, int] = {}
    for e in events:
        attrs = e.get("attrs") or {}
        w = str(attrs.get("worker_id") or "?")
        if e.get("kind") == "canary_fail":
            streaks[w] = streaks.get(w, 0) + 1
        elif e.get("kind") == "canary_ok":
            streaks[w] = 0
    live = {w: n for w, n in streaks.items() if n >= CANARY_FAIL_N}
    for w, n in sorted(live.items()):
        rep.add(WARN, f"canary {w}",
                f"{n} consecutive canary failures and no recovery: the "
                "worker is wedged (its breaker should be open — check "
                "breaker_transition events)")
    if streaks and not live:
        rep.add(OK, "canary", "probing active, no live failure streaks")


#: A standby not parked "ready" (warming/promoting) for longer than
#: this is stuck — warmup and joins are seconds, not minutes.
STANDBY_STUCK_S = 120.0
#: A pending scale directive older than this never applied.
SCALE_STUCK_S = 120.0
#: Scale direction changes in the timeline window that count as thrash.
SCALE_THRASH_N = 3
#: Canary failures after a worker_join that count as a rejected join.
CANARY_REJECT_N = 2


def check_autoscale(rep: Report, standbys: list[dict],
                    directives: list[dict],
                    events: list[dict] | None = None) -> None:
    """Autoscaling health (docs/RESILIENCE.md "Autoscaling"): standby
    pool state, stuck scale directives, and — given timeline events —
    scale thrash and canary-rejected joins. Pure function over the
    coordinator listings / timeline payload so it unit-tests without
    HTTP."""
    now = time.time()
    if not standbys and not directives and not events:
        return  # no autoscaling deployed: nothing to report
    ready = stuck = 0
    for s in standbys:
        state = s.get("state", "?")
        age = now - float(s.get("ts") or now)
        if state == "ready":
            ready += 1
        elif age > STANDBY_STUCK_S:
            stuck += 1
            rep.add(WARN, f"standby {s.get('worker', '?')}",
                    f"state={state} for {age:.0f}s — warmup or join is "
                    "wedged (a join should take seconds)")
    if standbys and not stuck:
        rep.add(OK, "standby pool",
                f"{len(standbys)} parked ({ready} ready to promote)")
    elif not standbys and directives:
        # Scale directives in flight but nothing warm to promote: the
        # next scale-out pays cold-start (minutes), not seconds.
        rep.add(WARN, "standby pool",
                "empty while scaling is active — launch workers with "
                "--standby so scale-outs promote instead of cold-start")
    for d in directives:
        age = now - float(d.get("ts") or now)
        if age > SCALE_STUCK_S:
            rep.add(WARN, f"scale directive {d.get('key', '?')}",
                    f"{d.get('action', '?')} pending {age:.0f}s without "
                    "applying — target dead or fenced out; the scaler "
                    "should have reaped it (planner down?)")
    if events:
        # Thrash: scale_out/scale_in direction flips in the window.
        actions = [e["attrs"].get("action") for e in events
                   if e.get("kind") == "planner_decision"
                   and (e.get("attrs") or {}).get("action")
                   in ("scale_out", "scale_out_cold", "scale_in")]
        flips = sum(1 for a, b in zip(actions, actions[1:])
                    if (a == "scale_in") != (b == "scale_in"))
        if flips >= SCALE_THRASH_N:
            rep.add(WARN, "autoscale thrash",
                    f"{flips} scale direction changes in the timeline "
                    "window — widen hysteresis/cooldown "
                    "(DTPU_PLANNER_CAPACITY_*)")
        elif actions:
            rep.add(OK, "autoscale",
                    f"{len(actions)} scale action(s), no thrash")
        # Canary-rejected joins: fails attributed to a recently-joined
        # worker with no admitting canary_ok after them.
        joined: set[str] = set()
        fails_after_join: dict[str, int] = {}
        for e in events:
            attrs = e.get("attrs") or {}
            kind = e.get("kind")
            if kind == "worker_join":
                joined.add(str(attrs.get("instance") or "?"))
            elif kind == "canary_fail":
                w = str(attrs.get("worker_id") or "?")
                if w in joined:
                    fails_after_join[w] = fails_after_join.get(w, 0) + 1
            elif kind == "canary_ok":
                fails_after_join.pop(str(attrs.get("worker_id") or "?"),
                                     None)
        for w, n in sorted(fails_after_join.items()):
            if n >= CANARY_REJECT_N:
                rep.add(WARN, f"canary-rejected join {w}",
                        f"worker joined but failed {n} canary probes and "
                        "was never admitted — it is held on probation; "
                        "if it was a standby promote, the scaler should "
                        "promote a replacement")


async def check_timeline(rep: Report, url: str) -> None:
    """Probe GET /debug/timeline and judge the decision plane."""
    import aiohttp
    url = url.rstrip("/")
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{url}/debug/timeline",
                                   timeout=aiohttp.ClientTimeout(10)) as r:
                if r.status != 200:
                    rep.add(FAIL, "/debug/timeline", f"HTTP {r.status}")
                    return
                timeline = await r.json()
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
        rep.add(FAIL, "/debug/timeline", f"{url}: {exc}")
        return
    check_decision_plane(rep, timeline)
    # Timeline-window autoscale judgments (thrash, rejected joins).
    check_autoscale(rep, [], [], events=timeline.get("events") or [])


async def run(args) -> int:
    rep = Report()
    check_imports(rep)
    if not args.no_devices:
        check_devices(rep)
    check_native(rep)
    if args.coordinator_url:
        await check_coordinator(rep, args.coordinator_url)
    else:
        rep.add(SKIP, "coordinator", "no --coordinator-url / DTPU_COORDINATOR_URL")
    if args.frontend_url:
        await check_frontend(rep, args.frontend_url)
        await check_observability(rep, args.frontend_url)
        await check_fleet_kv(rep, args.frontend_url)
        await check_kv_federation(rep, args.frontend_url)
        await check_adapters(rep, args.frontend_url)
        await check_perf(rep, args.frontend_url)
        await check_timeline(rep, args.frontend_url)
    n_fail = sum(1 for s, _, _ in rep.rows if s == FAIL)
    print(f"doctor: {len(rep.rows)} checks, {n_fail} failures", flush=True)
    return 1 if rep.failed else 0


def main() -> None:
    import os
    parser = argparse.ArgumentParser(description="dynamo-tpu deployment doctor")
    parser.add_argument("--coordinator-url",
                        default=os.environ.get("DTPU_COORDINATOR_URL"),
                        help="probe this control plane (tcp://host:port)")
    parser.add_argument("--frontend-url", default=None,
                        help="probe this OpenAI frontend (http://host:port)")
    parser.add_argument("--no-devices", action="store_true",
                        help="skip jax device probe (avoids backend init)")
    sys.exit(asyncio.run(run(parser.parse_args())))


if __name__ == "__main__":
    main()
