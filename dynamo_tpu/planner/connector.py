"""Scaling connectors: how planner decisions become replica changes.

Reference: the planner drives a Kubernetes connector
(components/planner/src/dynamo/planner/kube.py) that patches
DynamoGraphDeployment replica counts. Here the connector is an interface:
deployments provide one per substrate; FakeConnector records decisions for
tests and dry runs.
"""

from __future__ import annotations

import abc

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.connector")


class Connector(abc.ABC):
    @abc.abstractmethod
    async def scale(self, component: str, replicas: int) -> None:
        """Set the desired replica count for a worker component."""

    async def current(self, component: str) -> int | None:
        """Observed replica count, if the substrate can report it."""
        return None


class FakeConnector(Connector):
    """Records scale calls; optionally tracks a simulated replica count."""

    def __init__(self, initial: dict[str, int] | None = None):
        self.replicas: dict[str, int] = dict(initial or {})
        self.calls: list[tuple[str, int]] = []

    async def scale(self, component: str, replicas: int) -> None:
        self.calls.append((component, replicas))
        self.replicas[component] = replicas
        log.info("scale %s -> %d", component, replicas)

    async def current(self, component: str) -> int | None:
        return self.replicas.get(component)
