"""SLA-driven fleet autoscaling: the live capacity model + scaler loop.

The reference's planner derives a capacity table OFFLINE with its
profiler (PAPER.md §0 capability #2, the L6 planner/profiler) and then
schedules *how many* workers run. This module builds the same model
LIVE from data the fleet already publishes, and closes the loop:

- **CapacityModel** converts observed demand into a target worker
  count. Demand = active + waiting slots across the pool (the
  ForwardPassMetrics stream the planner already aggregates) plus the
  shared prefill-queue backlog; per-worker capacity = the admission-cap
  style concurrency limit (PERF_NOTES' "bs<=18 at SLO" measurements)
  times a utilization headroom, derated by the live roofline fraction
  from the perf plane when a worker is measurably slower than the
  model expects (``/debug/perf`` ``perf_roofline_frac``). SLO pressure
  (runtime/slo.py ``pressure()``) is the override lane: a burning
  fleet adds capacity even when the slot math says it fits, because
  burn means the slot math is wrong.
- **FleetScaler** applies the RoleReconfigurator's proven guard-rail
  discipline to worker COUNT: hysteresis (a direction must persist),
  cooldown between actions, at-most-one-action-in-flight fleet-wide,
  and min/max floors. Scale-out promotes a pre-warmed standby
  (llm/standby.py) via an epoch-fenced ``scale/`` directive riding the
  PLANNER's lease — a dead planner's scale-out can't apply — and falls
  back to the substrate connector (planner/connector.py) to backfill
  the standby pool cold. Scale-in picks the least-loaded serving
  worker and issues a retire directive; the worker drains through the
  role-flip machinery with typed ``incomplete:scale_in`` frames, so
  zero requests drop.

Epochs are minted strictly above EVERYTHING visible in the fleet —
role statuses, pending role-flip directives, pending scale directives
— so a scale directive racing a role flip shares one fence and exactly
one side applies (llm/reconfig.py rejects the loser typed).

Every decision journals as a ``planner_decision`` with an explicit
cause ref (the most recent ``slo_alert_fire`` when pressure drove it),
and the directive carries the decision ref, so ``/debug/timeline``
walks ``slo_alert_fire -> planner_decision(scale_out) ->
standby_promote -> worker_join -> canary_ok`` as one chain.

Metrics: ``dynamo_tpu_autoscale_*`` (docs/OBSERVABILITY.md). Knobs:
``DTPU_PLANNER_CAPACITY_<FIELD>`` env over ``CapacityConfig``.
"""

from __future__ import annotations

import dataclasses
import math
import time

from dynamo_tpu.llm.reconfig import ROLE_ROOT, ROLE_STATUS_ROOT, RoleState
from dynamo_tpu.llm.standby import SCALE_ROOT, STANDBY_ROOT, scale_key
from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.capacity")


@dataclasses.dataclass
class CapacityConfig:
    """Autoscaling knobs. All plain scalars so the generic
    ``DTPU_PLANNER_CAPACITY_<FIELD>`` env override applies
    (runtime/config.py ``_apply_scalar_env``)."""

    enabled: bool = False
    # The role promoted standbys serve / scale-in retires from, and the
    # connector component backfilling the standby pool.
    role: str = "decode"
    component: str = "tpu"
    min_workers: int = 1
    max_workers: int = 8
    # Admission-cap style per-worker concurrency at SLO (PERF_NOTES
    # measures bs<=18 on llama-3-8b int8; mockers are configured).
    slots_per_worker: int = 16
    # Headroom: plan to this fraction of the cap, not to saturation.
    target_utilization: float = 0.75
    # Guard rails (RoleReconfigurator discipline).
    hysteresis_intervals: int = 2
    cooldown_s: float = 60.0
    # SLO pressure level at/above which capacity is added regardless of
    # the slot math (burn means the slot math is wrong).
    pressure_level: int = 2
    # Prefill-queue backlog that counts as unserved demand.
    queue_depth_high: int = 8
    # Sustained utilization below this argues for scale-in.
    util_low: float = 0.30
    # Roofline derate: a worker measurably below the expected fraction
    # serves proportionally fewer slots at SLO; never derate below this
    # floor (a cold perf plane must not halve the fleet's capacity).
    derate_floor: float = 0.5
    # Drain budget on retire directives; 0 = worker default.
    drain_s: float = 0.0
    # A pending directive older than this is stuck: reap + replace.
    stuck_scale_s: float = 120.0


def apply_capacity_env(cfg: CapacityConfig) -> CapacityConfig:
    """Overlay DTPU_PLANNER_CAPACITY_* env vars onto ``cfg``."""
    from dynamo_tpu.runtime.config import _apply_scalar_env
    _apply_scalar_env("PLANNER_CAPACITY", cfg)
    return cfg


class CapacityModel:
    """Demand -> target worker count, with an EWMA so one noisy
    interval never moves capacity (the hysteresis above it handles the
    rest)."""

    def __init__(self, cfg: CapacityConfig, alpha: float = 0.5):
        self.cfg = cfg
        self.alpha = alpha
        self._demand_ewma: float | None = None

    def observe(self, active: int, waiting: int, queue_depth: int | None
                ) -> float:
        """Fold one interval's demand observation (concurrent request
        slots wanted fleet-wide) into the EWMA and return it."""
        demand = float(active + waiting + (queue_depth or 0))
        if self._demand_ewma is None:
            self._demand_ewma = demand
        else:
            self._demand_ewma = (self.alpha * demand
                                 + (1 - self.alpha) * self._demand_ewma)
        return self._demand_ewma

    def worker_capacity(self, roofline_frac: float | None = None,
                        expected_frac: float | None = None) -> float:
        """Effective concurrent slots one worker serves at SLO: the
        admission cap times headroom, derated by live-vs-expected
        roofline when the perf plane says this fleet runs slow."""
        cfg = self.cfg
        cap = cfg.slots_per_worker * cfg.target_utilization
        if roofline_frac and expected_frac and expected_frac > 0:
            cap *= min(1.0, max(cfg.derate_floor,
                                roofline_frac / expected_frac))
        return max(1e-9, cap)

    def target(self, current: int, pressure_level: int | None,
               queue_depth: int | None,
               roofline_frac: float | None = None,
               expected_frac: float | None = None) -> int:
        """The worker count the fleet should run, before guard rails."""
        cfg = self.cfg
        demand = self._demand_ewma or 0.0
        want = math.ceil(demand / self.worker_capacity(
            roofline_frac, expected_frac))
        if pressure_level is not None and pressure_level >= \
                cfg.pressure_level:
            # The SLO plane is burning: whatever the slot math says,
            # the fleet needs more capacity NOW.
            want = max(want, current + 1)
        if queue_depth is not None and queue_depth >= cfg.queue_depth_high:
            want = max(want, current + 1)
        return max(cfg.min_workers, min(cfg.max_workers, want))

    @property
    def demand(self) -> float:
        return self._demand_ewma or 0.0


class FleetScaler:
    """One planner's worker-count decision loop (the autoscaler).

    ``pressure_fn``/``queue_depth_fn``/``demand_fn``/``perf_fn`` are
    injectable signal sources (the planner wires defaults; tests
    script them). ``connector`` backfills the standby pool when a
    scale-out finds no warm standby. ``clock`` is injectable so the
    cooldown is fake-clock testable."""

    def __init__(self, client, namespace: str,
                 config: CapacityConfig | None = None,
                 connector=None, pressure_fn=None, queue_depth_fn=None,
                 demand_fn=None, perf_fn=None, clock=time.monotonic,
                 metrics=None):
        self._client = client
        self.namespace = namespace
        self.cfg = config or CapacityConfig()
        self.model = CapacityModel(self.cfg)
        self._connector = connector
        self._pressure_fn = pressure_fn
        self._queue_depth_fn = queue_depth_fn
        self._demand_fn = demand_fn
        self._perf_fn = perf_fn
        self._clock = clock
        self._last_action_t: float | None = None
        self._streak = {"out": 0, "in": 0}
        # Highest epoch this scaler ever saw or minted — kept across
        # directive GC so a reaped orphan's epoch is never re-used
        # (monotonic minting keeps resurrection stories fenceable).
        self._epoch_floor = 0
        self._last_decision_ref: str | None = None
        # Promote directives we issued: worker_hex -> issue monotonic t
        # (join latency is measured when the worker turns up serving).
        self._promotes_inflight: dict[str, float] = {}
        self.issued: list[dict] = []
        self._m_target = self._m_current = self._m_standby = None
        self._m_decisions = self._m_join = None
        if metrics is not None:
            m = metrics.namespace("autoscale")
            self._m_target = m.gauge(
                "autoscale_target_workers",
                "Capacity-model target worker count", ["role"])
            self._m_current = m.gauge(
                "autoscale_current_workers",
                "Serving workers the scaler counts", ["role"])
            self._m_standby = m.gauge(
                "autoscale_standby_pool",
                "Warm standbys available to promote")
            self._m_decisions = m.counter(
                "autoscale_decisions_total",
                "Scaler decisions by action", ["action"])
            self._m_join = m.gauge(
                "autoscale_join_seconds",
                "Last observed promote-to-serving join latency")

    # -- fleet view -----------------------------------------------------------
    async def fleet(self) -> list[dict]:
        items = await self._client.kv_get_prefix(
            f"{ROLE_STATUS_ROOT}{self.namespace}/")
        return [it["v"] for it in items if isinstance(it.get("v"), dict)]

    async def standbys(self) -> list[dict]:
        items = await self._client.kv_get_prefix(
            f"{STANDBY_ROOT}{self.namespace}/")
        return [it["v"] for it in items if isinstance(it.get("v"), dict)]

    async def pending(self) -> list[dict]:
        items = await self._client.kv_get_prefix(
            f"{SCALE_ROOT}{self.namespace}/")
        out = []
        for it in items:
            v = it.get("v")
            if isinstance(v, dict):
                out.append({"key": it["k"], **v})
        return out

    async def role_directives(self) -> list[dict]:
        items = await self._client.kv_get_prefix(
            f"{ROLE_ROOT}{self.namespace}/")
        return [{"key": it["k"], **it["v"]} for it in items
                if isinstance(it.get("v"), dict)]

    # -- one decision step ----------------------------------------------------
    async def step(self) -> dict:
        """Observe, model, guard, maybe issue ONE directive. Returns a
        decision record (``action`` says what happened)."""
        cfg = self.cfg
        pressure = self._pressure_fn() if self._pressure_fn else None
        p_level = pressure.level if pressure is not None else None
        depth = await self._maybe(self._queue_depth_fn)
        demand = await self._maybe(self._demand_fn) or (0, 0)
        perf = await self._maybe(self._perf_fn) or {}
        fleet = await self.fleet()
        standbys = [s for s in await self.standbys()
                    if s.get("state") in ("ready", None)]
        directives = await self.pending()
        directives = await self._gc(fleet, standbys, directives)
        serving = [s for s in fleet
                   if s.get("role") == cfg.role
                   and s.get("state") == RoleState.SERVING]
        current = len(serving)
        self._note_joins(serving)
        self.model.observe(int(demand[0]), int(demand[1]), depth)
        want = self.model.target(
            current, p_level, depth,
            roofline_frac=perf.get("roofline_frac"),
            expected_frac=perf.get("expected_frac"))
        record: dict = {
            "pool": "capacity", "action": "none",
            "pressure": pressure.to_wire() if pressure else None,
            "queue_depth": depth,
            "demand": round(self.model.demand, 2),
            "current": current, "standbys": len(standbys),
            "target": want,
        }
        self._set_gauges(want, current, len(standbys))
        direction = ("out" if want > current
                     else "in" if want < current else None)
        for k in self._streak:
            self._streak[k] = self._streak[k] + 1 if direction == k else 0
        record["signal"] = direction
        record["streaks"] = dict(self._streak)
        if direction is None:
            return record
        if self._streak[direction] < cfg.hysteresis_intervals:
            record["action"] = "hysteresis"
            return self._journal(record)
        now = self._clock()
        if (self._last_action_t is not None
                and now - self._last_action_t < cfg.cooldown_s):
            record["action"] = "cooldown"
            return self._journal(record)
        if self._action_in_flight(fleet, directives):
            record["action"] = "scale_in_flight"
            return self._journal(record)
        if direction == "out":
            return await self._scale_out(record, fleet, standbys,
                                         directives, now)
        return await self._scale_in(record, serving, fleet, directives, now)

    # -- scale-out -------------------------------------------------------------
    async def _scale_out(self, record: dict, fleet, standbys, directives,
                         now: float) -> dict:
        cfg = self.cfg
        if not standbys:
            # No warm standby: ask the substrate for a cold one. The
            # connector is the slow path — it backfills the pool, and a
            # later step promotes the worker once it parks warm.
            record["action"] = "scale_out_cold"
            self._journal(record)
            self._count(record["action"])
            if self._connector is not None:
                total = len(fleet) + len(standbys) + 1
                await self._connector.scale(cfg.component, total)
                record["connector_target"] = total
            self._last_action_t = now
            self._streak["out"] = 0
            return record
        target = standbys[0]
        epoch = self._next_epoch(fleet, directives,
                                 await self.role_directives())
        self._journal(dict(record, action="scale_out",
                           worker=target["worker"], epoch=epoch))
        directive = await self.issue(target["worker"], "promote",
                                     cfg.role, epoch,
                                     cause=self._last_decision_ref)
        self._count("scale_out")
        self._promotes_inflight[target["worker"]] = now
        self._last_action_t = now
        self._streak["out"] = 0
        record["action"] = "scale_out"
        record["directive"] = directive
        return record

    # -- scale-in --------------------------------------------------------------
    async def _scale_in(self, record: dict, serving, fleet, directives,
                        now: float) -> dict:
        cfg = self.cfg
        if len(serving) <= cfg.min_workers:
            record["action"] = "bounded"
            return self._journal(record)
        # Least-loaded serving worker drains fastest; never take the
        # last prefill-capable worker out of a disagg fleet.
        candidates = sorted(serving,
                            key=lambda s: int(s.get("inflight") or 0))
        victim = None
        for s in candidates:
            if s.get("role") in ("prefill", "agg"):
                others = [o for o in fleet if o is not s
                          and o.get("role") in ("prefill", "agg")]
                if not others:
                    continue
            victim = s
            break
        if victim is None:
            record["action"] = "bounded"
            return self._journal(record)
        epoch = self._next_epoch(fleet, directives,
                                 await self.role_directives())
        self._journal(dict(record, action="scale_in",
                           worker=victim["worker"], epoch=epoch))
        directive = await self.issue(victim["worker"], "retire", None,
                                     epoch, cause=self._last_decision_ref)
        self._count("scale_in")
        self._last_action_t = now
        self._streak["in"] = 0
        record["action"] = "scale_in"
        record["directive"] = directive
        return record

    async def issue(self, worker_hex: str, action: str, role: str | None,
                    epoch: int, issued_by: str = "planner",
                    cause: str | None = None) -> dict:
        """Write one scale directive on OUR lease (planner death ->
        lease expiry -> directive gone -> stale scale fenced)."""
        directive = {"action": action, "epoch": int(epoch),
                     "issued_by": issued_by, "ts": time.time()}
        if role is not None:
            directive["role"] = role
        if cause is not None:
            directive["cause"] = cause
        if action == "retire" and self.cfg.drain_s > 0:
            directive["drain_s"] = self.cfg.drain_s
        await self._client.kv_put(
            scale_key(self.namespace, int(worker_hex, 16)), directive,
            use_primary_lease=True)
        self.issued.append({"worker": worker_hex, **directive})
        log.info("issued %s -> %s (epoch %d)", action, worker_hex, epoch)
        return {"worker": worker_hex, **directive}

    # -- internals -------------------------------------------------------------
    @staticmethod
    async def _maybe(fn):
        if fn is None:
            return None
        try:
            res = fn()
            if hasattr(res, "__await__"):
                res = await res
            return res
        except (ConnectionError, OSError, RuntimeError):
            return None

    def _journal(self, record: dict) -> dict:
        """Every decision — including suppressed ones — lands on the
        decision plane. A pressure-driven scale-out names the most
        recent SLO page as its cause, closing the chain the timeline
        walks."""
        cause = None
        if record.get("action") in ("scale_out", "scale_out_cold"):
            cause = journal.recent_ref(EventKind.SLO_ALERT_FIRE)
        # NB ``worker=`` is emit()'s origin override — the TARGET worker
        # rides as a plain attr so the decision stays attributed to the
        # planner and its ref can't collide with the worker's own seqs.
        self._last_decision_ref = journal.emit(
            EventKind.PLANNER_DECISION, cause=cause,
            action=record.get("action"), signal=record.get("signal"),
            pressure=record.get("pressure"),
            queue_depth=record.get("queue_depth"),
            demand=record.get("demand"), current=record.get("current"),
            target=record.get("target"), standbys=record.get("standbys"),
            target_worker=record.get("worker"), epoch=record.get("epoch"))
        return record

    def _count(self, action: str) -> None:
        if self._m_decisions is not None:
            self._m_decisions.inc(action=action)

    def _set_gauges(self, want: int, current: int, standbys: int) -> None:
        if self._m_target is not None:
            self._m_target.set(want, role=self.cfg.role)
            self._m_current.set(current, role=self.cfg.role)
            self._m_standby.set(standbys)

    def _note_joins(self, serving: list[dict]) -> None:
        """A promoted worker turned up serving: record its join
        latency and clear the in-flight marker."""
        for s in serving:
            t0 = self._promotes_inflight.pop(s.get("worker"), None)
            if t0 is not None and self._m_join is not None:
                self._m_join.set(self._clock() - t0)

    def _action_in_flight(self, fleet: list[dict],
                          directives: list[dict]) -> bool:
        """At most one scale action in flight fleet-wide: any pending
        scale directive, any draining worker, or an unjoined promote."""
        cfg = self.cfg
        now = time.time()
        for s in fleet:
            if s.get("state") == RoleState.DRAINING:
                return True
        for d in directives:
            age = now - float(d.get("ts") or now)
            if cfg.stuck_scale_s > 0 and age > cfg.stuck_scale_s:
                log.warning("ignoring stuck scale directive %s (%.0fs old)",
                            d.get("key"), age)
                continue
            return True
        return False

    def _next_epoch(self, fleet: list[dict], scale_directives: list[dict],
                    role_directives: list[dict]) -> int:
        """Strictly above EVERY epoch visible in the fleet — including
        pending role-flip directives, so a scale directive racing a
        flip shares one fence and exactly one side applies."""
        top = self._epoch_floor
        for s in fleet:
            top = max(top, int(s.get("epoch") or 0))
        for d in scale_directives + role_directives:
            top = max(top, int(d.get("epoch") or 0))
        self._epoch_floor = top + 1
        return top + 1

    async def _gc(self, fleet: list[dict], standbys: list[dict],
                  directives: list[dict]) -> list[dict]:
        """Reap applied/orphaned scale directives (same contract as the
        reconfigurator's GC: a directive is a pending verb, not desired
        state). An orphaned PROMOTE — its standby died mid-join (no
        standby key, no rolestatus) — journals so the replacement
        promotion is attributable."""
        by_worker = {s.get("worker"): s for s in fleet}
        standby_ids = {s.get("worker") for s in standbys}
        keep = []
        for d in directives:
            self._epoch_floor = max(self._epoch_floor,
                                    int(d.get("epoch") or 0))
            worker = d["key"].rsplit("/", 1)[-1]
            status = by_worker.get(worker)
            applied = (status is not None
                       and int(status.get("epoch") or 0)
                       >= int(d.get("epoch") or 0))
            orphaned = (d.get("action") == "promote"
                        and status is None
                        and worker not in standby_ids)
            retired_gone = d.get("action") == "retire" and status is None
            if applied or orphaned or retired_gone:
                if orphaned:
                    self._last_decision_ref = journal.emit(
                        EventKind.PLANNER_DECISION,
                        cause=d.get("cause"),
                        action="promote_orphaned", worker=worker,
                        epoch=d.get("epoch"))
                    self._count("promote_orphaned")
                    # The join died with the standby: clear the fence
                    # so the replacement promotion isn't counted as an
                    # action already in flight.
                    self._promotes_inflight.pop(worker, None)
                    self._last_action_t = None
                try:
                    await self._client.kv_delete(d["key"])
                except (ConnectionError, OSError, RuntimeError):
                    pass
                continue
            keep.append(d)
        return keep
