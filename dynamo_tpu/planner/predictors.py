"""Load predictors (reference planner_core load predictors: constant /
ARIMA / prophet — components/planner/src/dynamo/planner/utils/load_predictor.py).
The heavy statistical models are deliberately replaced with transparent
equivalents: serving-load horizons are one adjustment interval (~seconds),
where last-value, windowed-mean, and linear-trend extrapolation cover the
useful signal without pulling in forecasting stacks.
"""

from __future__ import annotations

from collections import deque


class ConstantPredictor:
    """Next value = last observed value."""

    def __init__(self, **_):
        self._last = 0.0

    def observe(self, value: float) -> None:
        self._last = float(value)

    def predict(self) -> float:
        return self._last


class MovingAveragePredictor:
    """Next value = mean of the last ``window`` observations."""

    def __init__(self, window: int = 8, **_):
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._values.append(float(value))

    def predict(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0


class LinearTrendPredictor:
    """Least-squares linear extrapolation one step ahead over the window
    (clamped at zero). Reacts to ramps the averaging predictors lag on."""

    def __init__(self, window: int = 8, **_):
        self._values: deque[float] = deque(maxlen=max(2, window))

    def observe(self, value: float) -> None:
        self._values.append(float(value))

    def predict(self) -> float:
        n = len(self._values)
        if n == 0:
            return 0.0
        if n == 1:
            return self._values[0]
        xs = range(n)
        mean_x = (n - 1) / 2
        mean_y = sum(self._values) / n
        denom = sum((x - mean_x) ** 2 for x in xs)
        slope = sum((x - mean_x) * (y - mean_y)
                    for x, y in zip(xs, self._values)) / denom
        return max(0.0, mean_y + slope * (n - mean_x))


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving_average": MovingAveragePredictor,
    "linear": LinearTrendPredictor,
}


def make_predictor(name: str, **kw):
    if name not in PREDICTORS:
        raise ValueError(f"unknown predictor {name!r}; have {sorted(PREDICTORS)}")
    return PREDICTORS[name](**kw)
