"""``python -m dynamo_tpu.planner`` — the planner as a deployable process.

Reference: ``python -m dynamo.planner`` (components/planner). Consumes the
workers' ForwardPassMetrics pub/sub stream through the coordinator and
scales worker pools through the selected connector:

- ``--connector kube``: patch StatefulSet replica counts via the
  Kubernetes API (planner/kube.py) — the deployment rendered by
  deploy_graph.py names StatefulSets ``<graph>-<component>``.
- ``--connector log`` (default): record decisions only (dry-run, the
  reference planner's no-op mode) — safe everywhere.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.planner.capacity import CapacityConfig, apply_capacity_env
from dynamo_tpu.planner.connector import FakeConnector
from dynamo_tpu.planner.core import Planner, PlannerConfig
from dynamo_tpu.planner.reconfig import ReconfigConfig, apply_reconfig_env
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.main")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu SLA planner")
    p.add_argument("--namespace", default=None)
    p.add_argument("--decode-component", default="tpu")
    p.add_argument("--prefill-component", default=None,
                   help="set for disaggregated deployments")
    p.add_argument("--adjustment-interval", type=float, default=10.0)
    p.add_argument("--predictor", default="moving_average",
                   choices=["constant", "moving_average", "linear"])
    p.add_argument("--max-num-seqs-per-worker", type=int, default=32)
    p.add_argument("--target-utilization", type=float, default=0.8)
    p.add_argument("--prefill-capacity-tok-s", type=float, default=8000.0)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--connector", default="log", choices=["log", "kube"])
    p.add_argument("--graph-name", default=None,
                   help="kube connector: the deploy_graph graph name "
                        "(StatefulSets are <graph>-<component>)")
    p.add_argument("--kube-url", default=None,
                   help="kube connector: API server base URL override "
                        "(default: in-cluster env)")
    p.add_argument("--coordinator-url", default=None)
    p.add_argument("--model-name", default=None,
                   help="served model name: enables the prefill-queue "
                        "depth signal for --reconfig")
    p.add_argument("--reconfig", action="store_true",
                   help="drive live prefill/decode role flips from SLO "
                        "pressure + prefill-queue depth (knobs via "
                        "DTPU_PLANNER_RECONFIG_*; llm/reconfig.py)")
    p.add_argument("--autoscale", action="store_true",
                   help="drive worker COUNT from the live capacity "
                        "model: promote pre-warmed standbys on "
                        "sustained SLO burn, retire the least-loaded "
                        "worker on sustained headroom (knobs via "
                        "DTPU_PLANNER_CAPACITY_*; planner/capacity.py)")
    p.add_argument("--autoscale-role", default="decode",
                   help="the role promoted standbys serve")
    p.add_argument("--autoscale-min", type=int, default=1)
    p.add_argument("--autoscale-max", type=int, default=8)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)
    try:
        if args.connector == "kube":
            from dynamo_tpu.planner.kube import (KubernetesAPI,
                                                 KubernetesConnector)
            if not args.graph_name:
                raise SystemExit("--connector kube needs --graph-name")
            connector = KubernetesConnector(
                args.graph_name,
                api=KubernetesAPI(base_url=args.kube_url))
        else:
            connector = FakeConnector()
        planner = Planner(PlannerConfig(
            namespace=cfg.namespace,
            decode_component=args.decode_component,
            prefill_component=args.prefill_component,
            adjustment_interval_s=args.adjustment_interval,
            predictor=args.predictor,
            max_num_seqs_per_worker=args.max_num_seqs_per_worker,
            target_utilization=args.target_utilization,
            prefill_capacity_tok_s=args.prefill_capacity_tok_s,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            model_name=args.model_name,
            reconfig=apply_reconfig_env(
                ReconfigConfig(enabled=args.reconfig)),
            capacity=apply_capacity_env(CapacityConfig(
                enabled=args.autoscale, role=args.autoscale_role,
                component=args.decode_component,
                min_workers=args.autoscale_min,
                max_workers=args.autoscale_max)),
        ), connector, runtime=runtime)
        await planner.start()
        print(f"PLANNER_READY connector={args.connector} "
              f"decode={args.decode_component} "
              f"prefill={args.prefill_component or '-'}", flush=True)
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        await runtime.wait_for_shutdown()
        await planner.stop()
    finally:
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
