"""SLA planner: load prediction -> replica scaling decisions.

Capability parity with the reference planner component
(components/planner/src/dynamo/planner/utils/planner_core.py:55): a
metrics-driven loop that predicts next-interval load per worker pool and
asks a connector to scale prefill/decode replica counts, informed by a
profiler-built capacity table (benchmarks/profiler/profile_sla.py:52).
"""

from dynamo_tpu.planner.capacity import (
    CapacityConfig,
    CapacityModel,
    FleetScaler,
    apply_capacity_env,
)
from dynamo_tpu.planner.connector import Connector, FakeConnector
from dynamo_tpu.planner.core import Planner, PlannerConfig, PoolState
from dynamo_tpu.planner.predictors import (
    ConstantPredictor,
    LinearTrendPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from dynamo_tpu.planner.profiler import (
    choose_capacity,
    profile_sweep,
)
from dynamo_tpu.planner.reconfig import (
    ReconfigConfig,
    RoleReconfigurator,
    apply_reconfig_env,
)

__all__ = [
    "Connector", "FakeConnector", "Planner", "PlannerConfig", "PoolState",
    "ConstantPredictor", "LinearTrendPredictor", "MovingAveragePredictor",
    "make_predictor", "choose_capacity", "profile_sweep",
    "ReconfigConfig", "RoleReconfigurator", "apply_reconfig_env",
    "CapacityConfig", "CapacityModel", "FleetScaler", "apply_capacity_env",
]
