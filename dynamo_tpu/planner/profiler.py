"""Pre-deployment profiling sweep (reference
benchmarks/profiler/profile_sla.py:52): measure TTFT/ITL/throughput over
an (ISL, OSL, concurrency) grid and derive the per-worker capacity
numbers the planner consumes.

The sweep drives the timing-faithful Mocker engine by default (CI,
capacity modeling of arbitrary speeds) — point it at a real TPUEngine via
``engine_factory`` for hardware numbers.
"""

from __future__ import annotations

import asyncio
import json
import time

import numpy as np

from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.profiler")


async def _measure_point(engine, isl: int, osl: int, concurrency: int,
                         vocab: int = 1000) -> dict:
    rng = np.random.default_rng(isl * 7919 + osl * 104729 + concurrency)

    async def one():
        req = PreprocessedRequest(
            model="profile",
            token_ids=rng.integers(0, vocab, size=isl).tolist())
        req.stop_conditions.max_tokens = osl
        req.stop_conditions.ignore_eos = True
        t0 = time.monotonic()
        t_first = None
        n = 0
        async for out in engine.generate(req, Context()):
            got = len(out.get("token_ids", []))
            if got and t_first is None:
                t_first = time.monotonic()
            n += got
            if out.get("finish_reason"):
                break
        t_end = time.monotonic()
        itl = ((t_end - t_first) / max(1, n - 1)) if t_first else 0.0
        return (t_first - t0 if t_first else 0.0), itl, n, t_end - t0

    t0 = time.monotonic()
    results = await asyncio.gather(*[one() for _ in range(concurrency)])
    elapsed = time.monotonic() - t0
    ttfts = sorted(r[0] for r in results)
    itls = sorted(r[1] for r in results)
    total = sum(r[2] for r in results)
    return {
        "isl": isl, "osl": osl, "concurrency": concurrency,
        "ttft_p50_ms": 1e3 * ttfts[len(ttfts) // 2],
        "ttft_p99_ms": 1e3 * ttfts[min(len(ttfts) - 1,
                                       int(len(ttfts) * 0.99))],
        "itl_p50_ms": 1e3 * itls[len(itls) // 2],
        "decode_tok_s": total / elapsed,
        "prefill_tok_s": isl * concurrency / max(1e-9, ttfts[-1]),
    }


async def profile_sweep(engine_factory, grid: list[tuple[int, int, int]],
                        output_path: str | None = None) -> dict:
    """Run the grid; returns {"points": [...]} and optionally writes JSON.

    ``engine_factory() -> engine`` builds a fresh engine per point so KV
    state doesn't leak between configurations.
    """
    points = []
    for isl, osl, conc in grid:
        engine = engine_factory()
        try:
            point = await _measure_point(engine, isl, osl, conc)
        finally:
            stop = getattr(engine, "stop", None)
            if stop is not None:
                res = stop()
                if asyncio.iscoroutine(res):
                    await res
        log.info("profiled isl=%d osl=%d conc=%d: ttft_p99=%.0fms "
                 "decode=%.0f tok/s", isl, osl, conc,
                 point["ttft_p99_ms"], point["decode_tok_s"])
        points.append(point)
    table = {"points": points}
    if output_path:
        def _dump() -> None:
            with open(output_path, "w") as fh:
                json.dump(table, fh, indent=2)

        await asyncio.to_thread(_dump)
    return table


def choose_capacity(table: dict, ttft_sla_ms: float,
                    itl_sla_ms: float) -> dict:
    """Pick the highest-throughput grid point meeting both SLAs
    (profile_sla.py's selection step). Returns the capacity facts the
    planner config consumes."""
    ok = [p for p in table["points"]
          if p["ttft_p99_ms"] <= ttft_sla_ms and p["itl_p50_ms"] <= itl_sla_ms]
    if not ok:
        raise ValueError(
            f"no profiled configuration meets ttft<={ttft_sla_ms}ms and "
            f"itl<={itl_sla_ms}ms; best points: "
            f"{sorted(table['points'], key=lambda p: p['ttft_p99_ms'])[:2]}")
    best = max(ok, key=lambda p: p["decode_tok_s"])
    return {
        "max_concurrency": best["concurrency"],
        "prefill_capacity_tok_s": best["prefill_tok_s"],
        "decode_capacity_tok_s": best["decode_tok_s"],
        "point": best,
    }
