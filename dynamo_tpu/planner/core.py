"""Planner core loop (reference planner_core.py:55 _Planner).

Consumes the workers' ForwardPassMetrics stream (the same pub/sub plane
the KV router reads), aggregates per-pool load, predicts one adjustment
interval ahead, and asks the connector for replica counts:

- decode pool: replicas sized so predicted concurrent requests fit within
  per-worker slot capacity at a utilization headroom;
- prefill pool (disaggregated deployments): replicas sized from predicted
  prefill token throughput against the profiler-measured per-worker
  capacity (profiler.choose_capacity).

Guard rails mirror the reference: min/max replica bounds, scale-down
hysteresis, and an adjustment cooldown so decisions don't flap.

Beyond replica counts, the planner can also re-partition a FIXED pool:
with ``reconfig.enabled`` it drives live prefill/decode role flips from
the SLO plane's pressure signal and the prefill-queue depth
(planner/reconfig.py; worker protocol in llm/reconfig.py) — the
runtime-reconfigurable xPyD story (PAPER.md §0 capability #1).
"""

from __future__ import annotations

import asyncio
import dataclasses
import math
import time

from dynamo_tpu.llm.kv_router.protocols import (ForwardPassMetrics,
                                                load_metrics_subject)
from dynamo_tpu.planner.capacity import CapacityConfig, FleetScaler
from dynamo_tpu.planner.connector import Connector
from dynamo_tpu.planner.predictors import make_predictor
from dynamo_tpu.planner.reconfig import ReconfigConfig, RoleReconfigurator
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner")


@dataclasses.dataclass
class PlannerConfig:
    namespace: str = "dynamo"
    decode_component: str = "tpu"
    prefill_component: str | None = None  # None = aggregated deployment
    adjustment_interval_s: float = 10.0
    predictor: str = "moving_average"
    predictor_window: int = 6
    # Decode sizing.
    max_num_seqs_per_worker: int = 32
    target_utilization: float = 0.8  # headroom before scaling up
    # Prefill sizing (tokens/s one worker sustains within the TTFT SLA;
    # normally filled from profiler.choose_capacity).
    prefill_capacity_tok_s: float = 8000.0
    min_replicas: int = 1
    max_replicas: int = 8
    # Consecutive under-loaded intervals required before scaling down.
    scale_down_patience: int = 3
    # Served model name: enables the prefill-queue depth signal for role
    # reconfiguration (queue_name(model_name) on the coordinator).
    model_name: str | None = None
    # Live role-flip decisions (planner/reconfig.py); knobs overridable
    # via DTPU_PLANNER_RECONFIG_<FIELD>.
    reconfig: ReconfigConfig = dataclasses.field(
        default_factory=ReconfigConfig)
    # SLA-driven autoscaling (planner/capacity.py); knobs overridable
    # via DTPU_PLANNER_CAPACITY_<FIELD>. When enabled it OWNS worker
    # count for its role — the legacy per-pool replica deciders stand
    # down so two loops never fight over the same StatefulSet.
    capacity: CapacityConfig = dataclasses.field(
        default_factory=CapacityConfig)


class PoolState:
    """Aggregated view of one worker pool from its metrics stream."""

    def __init__(self, predictor: str, window: int):
        self.workers: dict[int, ForwardPassMetrics] = {}
        self.last_seen: dict[int, float] = {}
        self.load_pred = make_predictor(predictor, window=window)
        self.tok_pred = make_predictor(predictor, window=window)

    def observe(self, worker_id: int, metrics: ForwardPassMetrics) -> None:
        self.workers[worker_id] = metrics
        self.last_seen[worker_id] = time.monotonic()

    def snapshot(self, stale_s: float = 30.0) -> dict:
        now = time.monotonic()
        live = {w: m for w, m in self.workers.items()
                if now - self.last_seen.get(w, 0) < stale_s}
        active = sum(m.worker_stats.request_active_slots for m in live.values())
        waiting = sum(m.worker_stats.num_requests_waiting for m in live.values())
        return {"workers": len(live), "active": active, "waiting": waiting,
                "live": live}


class Planner:
    def __init__(self, config: PlannerConfig, connector: Connector,
                 runtime=None):
        self.config = config
        self.connector = connector
        self._runtime = runtime
        self.decode = PoolState(config.predictor, config.predictor_window)
        self.prefill = (PoolState(config.predictor, config.predictor_window)
                        if config.prefill_component else None)
        self._below: dict[str, int] = {"decode": 0, "prefill": 0}
        self._subs: list = []
        self._tasks: list[asyncio.Task] = []
        self.decisions: list[dict] = []
        # Role-flip loop: constructed in start() (needs the coordinator),
        # or injected directly by tests / embedded deployments.
        self.reconfigurator: RoleReconfigurator | None = None
        # Autoscaler (planner/capacity.py): same injection contract.
        self.scaler: FleetScaler | None = None

    # -- metrics intake -------------------------------------------------------
    async def start(self) -> None:
        """Subscribe to the pools' metrics subjects (needs a runtime)."""
        assert self._runtime is not None
        client = self._runtime.require_coordinator()
        cfg = self.config
        pools = [(cfg.decode_component, self.decode)]
        if self.prefill is not None:
            pools.append((cfg.prefill_component, self.prefill))
        for comp, pool in pools:
            sub = await client.subscribe(
                load_metrics_subject(cfg.namespace, comp))
            self._subs.append(sub)
            self._tasks.append(asyncio.create_task(self._intake(sub, pool)))
        if cfg.reconfig.enabled and self.reconfigurator is None:
            self.reconfigurator = RoleReconfigurator(
                client, cfg.namespace, cfg.reconfig,
                pressure_fn=self._slo_pressure,
                queue_depth_fn=(self._queue_depth
                                if cfg.model_name else None))
        if cfg.capacity.enabled and self.scaler is None:
            self.scaler = FleetScaler(
                client, cfg.namespace, cfg.capacity,
                connector=self.connector,
                pressure_fn=self._slo_pressure,
                queue_depth_fn=(self._queue_depth
                                if cfg.model_name else None),
                demand_fn=self._demand,
                metrics=getattr(self._runtime, "metrics", None))
        # Decision plane: the planner's reconfig decisions (and their
        # input signals) ride the journal subject into the frontend's
        # merged /debug/timeline, same as worker journals.
        from dynamo_tpu.runtime.journal import JournalPublisher, get_journal
        get_journal().worker = "planner"
        self._journal_pub = JournalPublisher(client, cfg.namespace, "planner")
        self._journal_pub.start_periodic()
        self._tasks.append(asyncio.create_task(self._loop()))

    @staticmethod
    def _slo_pressure():
        """Default pressure source: the process-global SLO plane (level 0
        when no targets are configured — reconfig then rides the queue
        signal alone)."""
        from dynamo_tpu.runtime import slo
        plane = slo.get_plane()
        return plane.pressure() if plane.enabled else None

    async def _queue_depth(self) -> int:
        from dynamo_tpu.llm.prefill_queue import queue_name
        client = self._runtime.require_coordinator()
        return await client.queue_len(queue_name(self.config.model_name))

    def _demand(self) -> tuple[int, int]:
        """Capacity-model demand source: (active, waiting) slots across
        the decode pool's live metrics stream."""
        snap = self.decode.snapshot()
        return snap["active"], snap["waiting"]

    async def stop(self) -> None:
        pub = getattr(self, "_journal_pub", None)
        if pub is not None:
            pub.stop_periodic()
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            await s.cancel()

    async def _intake(self, sub, pool: PoolState) -> None:
        async for msg in sub:
            payload = msg["payload"]
            try:
                m = ForwardPassMetrics.from_wire(payload)
                pool.observe(m.worker_id or 0, m)
            except (KeyError, TypeError, ValueError):
                log.warning("malformed metrics payload: %r", payload)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.adjustment_interval_s)
            try:
                await self.step()
            except Exception:  # noqa: BLE001
                log.exception("planner step failed")

    # -- decisions ------------------------------------------------------------
    def _bounded(self, n: int) -> int:
        return max(self.config.min_replicas,
                   min(self.config.max_replicas, n))

    async def _decide(self, pool_name: str, component: str, snap: dict,
                      demand: float, predictor, capacity: float) -> dict:
        """Shared observe -> predict -> bound -> hysteresis -> scale step
        for one pool."""
        cfg = self.config
        predictor.observe(demand)
        predicted = predictor.predict()
        want = self._bounded(math.ceil(predicted / max(1e-9, capacity)))
        current = await self.connector.current(component)
        if current is None:
            current = snap["workers"] or cfg.min_replicas
        decide = current
        if want > current:
            decide = want
            self._below[pool_name] = 0
        elif want < current:
            # Hysteresis: only shrink after sustained low demand.
            self._below[pool_name] += 1
            if self._below[pool_name] >= cfg.scale_down_patience:
                decide = want
                self._below[pool_name] = 0
        else:
            self._below[pool_name] = 0
        record = {"pool": pool_name, "demand": demand,
                  "predicted": predicted, "current": current,
                  "target": decide}
        if decide != current:
            await self.connector.scale(component, decide)
        self.decisions.append(record)
        return record

    async def step(self) -> dict:
        """One adjustment: observe, predict, decide, scale per pool.
        Returns the decision records (also appended to self.decisions)."""
        cfg = self.config
        capacity_record = None
        if self.scaler is not None and cfg.capacity.enabled:
            try:
                capacity_record = await self.scaler.step()
                self.decisions.append(capacity_record)
            except (ConnectionError, OSError, RuntimeError):
                # The rest of the step must survive a flaky control
                # plane; the next interval retries.
                log.warning("capacity scaler step failed", exc_info=True)
        reconfig_record = None
        if self.reconfigurator is not None and self.config.reconfig.enabled:
            try:
                reconfig_record = await self.reconfigurator.step()
                self.decisions.append(reconfig_record)
            except (ConnectionError, OSError, RuntimeError):
                log.warning("role reconfig step failed", exc_info=True)
        if self.scaler is not None and cfg.capacity.enabled:
            # The autoscaler owns worker count: the legacy per-pool
            # replica deciders stand down (two loops patching the same
            # StatefulSet would fight).
            out = {"capacity": capacity_record}
            if reconfig_record is not None:
                out["reconfig"] = reconfig_record
            return out
        snap = self.decode.snapshot()
        record = await self._decide(
            "decode", cfg.decode_component, snap,
            snap["active"] + snap["waiting"], self.decode.load_pred,
            cfg.max_num_seqs_per_worker * cfg.target_utilization)
        if self.prefill is None:
            out = {"decode": record}
            if reconfig_record is not None:
                out["reconfig"] = reconfig_record
            return out
        psnap = self.prefill.snapshot()
        # Prefill demand proxy: queued-request pressure (LIVE workers only
        # — dead workers' last metrics must not inflate demand forever)
        # times a nominal prompt length, against profiled throughput.
        ptok = sum((m.worker_stats.num_requests_waiting or 0)
                   for m in psnap["live"].values()) * 512.0
        precord = await self._decide(
            "prefill", cfg.prefill_component, psnap, ptok,
            self.prefill.tok_pred, cfg.prefill_capacity_tok_s)
        out = {"decode": record, "prefill": precord}
        if reconfig_record is not None:
            out["reconfig"] = reconfig_record
        return out
