"""Kubernetes connector: planner decisions -> StatefulSet scale patches.

The reference planner patches DynamoGraphDeployment replica counts through
its operator (components/planner/src/dynamo/planner/kube.py
KubernetesAPI, kubernetes_connector.py KubernetesConnector). This repo
deploys workers as plain StatefulSets rendered by deploy_graph.py (no
CRD/operator), so the connector scales those directly via the
``/scale`` subresource of the apps/v1 API.

Deliberately stdlib-only (urllib + ssl): the ``kubernetes`` client
package is not a dependency, and the three calls needed (GET
statefulset, GET/PATCH scale) don't justify one. In-cluster config is
read from the service-account mount exactly like the official client;
tests point ``base_url`` at a fake API server
(tests/test_planner_kube.py, mirroring the reference's
components/planner/test/kube.py harness).
"""

from __future__ import annotations

import asyncio
import json
import os
import ssl
import urllib.error
import urllib.request

from dynamo_tpu.planner.connector import Connector
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.kube")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def current_namespace(default: str = "default") -> str:
    """The pod's namespace when running in-cluster (service-account
    mount), else ``default`` (reference kube.py
    get_current_k8s_namespace)."""
    try:
        # dtpu: ignore[blocking-call-in-async] -- one-line service-account mount, read once at connector construction
        with open(os.path.join(SA_DIR, "namespace"), encoding="utf-8") as fh:
            return fh.read().strip()
    except FileNotFoundError:
        return default


class KubernetesAPI:
    """Minimal apps/v1 client for StatefulSet scale operations.

    ``base_url``/``token`` default to the in-cluster environment
    (KUBERNETES_SERVICE_HOST/PORT + the mounted service-account token and
    CA). Blocking I/O runs on executor threads behind the async API.
    """

    def __init__(self, base_url: str | None = None,
                 token: str | None = None,
                 namespace: str | None = None,
                 ca_file: str | None = None):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a cluster (no KUBERNETES_SERVICE_HOST) and no "
                    "base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        if token is None:
            try:
                # dtpu: ignore[blocking-call-in-async] -- one-line service-account mount, read once at connector construction
                with open(os.path.join(SA_DIR, "token"),
                          encoding="utf-8") as fh:
                    token = fh.read().strip()
            except FileNotFoundError:
                token = None
        self.token = token
        self.namespace = namespace or current_namespace()
        if ca_file is None:
            default_ca = os.path.join(SA_DIR, "ca.crt")
            ca_file = default_ca if os.path.exists(default_ca) else None
        self._ssl = (ssl.create_default_context(cafile=ca_file)
                     if self.base_url.startswith("https") else None)

    # -- sync core (executor) ------------------------------------------------
    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json") -> dict:
        url = self.base_url + path
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=15,
                                        context=self._ssl) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")[:500]
            raise KubeAPIError(exc.code, f"{method} {path}: {detail}") \
                from exc

    def _sts_path(self, name: str, sub: str = "") -> str:
        return (f"/apis/apps/v1/namespaces/{self.namespace}"
                f"/statefulsets/{name}{sub}")

    # -- async API ------------------------------------------------------------
    async def get_statefulset(self, name: str) -> dict | None:
        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._request, "GET", self._sts_path(name))
        except KubeAPIError as exc:
            if exc.status == 404:
                return None
            raise

    async def get_replicas(self, name: str) -> int | None:
        try:
            scale = await asyncio.get_running_loop().run_in_executor(
                None, self._request, "GET", self._sts_path(name, "/scale"))
        except KubeAPIError as exc:
            if exc.status == 404:
                return None
            raise
        return int((scale.get("spec") or {}).get("replicas", 0))

    async def set_replicas(self, name: str, replicas: int) -> None:
        body = {"spec": {"replicas": int(replicas)}}
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._request(
                "PATCH", self._sts_path(name, "/scale"), body,
                "application/merge-patch+json"))

    # -- generic manifests (deploy_graph re-render loop) ----------------------
    def _collection_path(self, manifest: dict) -> str:
        """Collection URL for a namespaced manifest. Plural = lowercased
        kind + 's' — correct for every kind the graph renderer emits
        (Deployment, StatefulSet, Service, ConfigMap, ServiceAccount,
        Role, RoleBinding)."""
        api = manifest.get("apiVersion", "v1")
        plural = manifest["kind"].lower() + "s"
        prefix = "/api/v1" if api == "v1" else f"/apis/{api}"
        return f"{prefix}/namespaces/{self.namespace}/{plural}"

    async def apply(self, manifest: dict) -> str:
        """Create-or-replace one manifest. Returns "created" |
        "replaced". (GET -> POST on 404, else PUT carrying the live
        resourceVersion — the stdlib-client equivalent of kubectl
        apply for the renderer's fully-specified manifests.)"""
        name = manifest["metadata"]["name"]
        base = self._collection_path(manifest)

        def do() -> str:
            try:
                cur = self._request("GET", f"{base}/{name}")
            except KubeAPIError as exc:
                if exc.status != 404:
                    raise
                self._request("POST", base, manifest)
                return "created"
            body = dict(manifest)
            md = dict(body.get("metadata") or {})
            rv = (cur.get("metadata") or {}).get("resourceVersion")
            if rv:
                md["resourceVersion"] = rv
            body["metadata"] = md
            self._request("PUT", f"{base}/{name}", body)
            return "replaced"

        return await asyncio.get_running_loop().run_in_executor(None, do)


class KubeAPIError(RuntimeError):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


class KubernetesConnector(Connector):
    """Scales the StatefulSets deploy_graph.py renders: component ``c`` of
    graph ``g`` lives in StatefulSet ``g-c`` (deploy_graph._component_name).
    Reference: kubernetes_connector.py (set_component_replicas /
    add_component).

    Error discipline: an unreachable/flaky API server retries under the
    unified ``policies.KUBE_SCALE`` curve (runtime/retry.py, bounded);
    exhausting it journals a typed ``planner_decision`` failure and
    returns instead of raising into the planner's ``step()`` — the next
    adjustment interval re-decides from fresh signals, which is the
    correct retry for a scaling loop. Kubernetes API *rejections*
    (KubeAPIError: RBAC, bad namespace) are real configuration bugs
    and still propagate."""

    def __init__(self, graph_name: str, api: KubernetesAPI | None = None):
        self.graph_name = graph_name
        self.api = api or KubernetesAPI()
        self.scale_failures = 0

    def _sts(self, component: str) -> str:
        return f"{self.graph_name}-{component}"

    async def scale(self, component: str, replicas: int) -> None:
        from dynamo_tpu.runtime import journal
        from dynamo_tpu.runtime.journal import EventKind
        from dynamo_tpu.runtime.retry import Backoff, policies
        name = self._sts(component)
        backoff = Backoff(policies.KUBE_SCALE)
        while True:
            try:
                await self.api.set_replicas(name, replicas)
                log.info("scaled %s -> %d replicas", name, replicas)
                return
            except KubeAPIError:
                raise  # API rejection: a config bug, not a transient
            except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
                if await backoff.sleep():
                    continue
                self.scale_failures += 1
                journal.emit(
                    EventKind.PLANNER_DECISION, action="scale_failed",
                    component=component, target=replicas,
                    error=f"{type(exc).__name__}: {exc}",
                    attempts=backoff.attempt)
                log.warning("scale %s -> %d failed after %d attempts: %s "
                            "(next interval retries)", name, replicas,
                            backoff.attempt, exc)
                return

    async def current(self, component: str) -> int | None:
        from dynamo_tpu.runtime.retry import Backoff, policies
        backoff = Backoff(policies.KUBE_SCALE)
        while True:
            try:
                return await self.api.get_replicas(self._sts(component))
            except KubeAPIError:
                raise
            except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
                if await backoff.sleep():
                    continue
                log.warning("get_replicas %s failed: %s (treating as "
                            "unknown)", component, exc)
                return None
