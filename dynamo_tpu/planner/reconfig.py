"""Planner-driven role reconfiguration: deciding WHICH worker flips WHEN.

The worker-side protocol (llm/reconfig.py) makes a role flip safe; this
module makes it *useful*: it closes the loop between the SLO plane's
TTFT/ITL pressure signals (runtime/slo.py ``pressure()``), the shared
prefill queue's depth (llm/prefill_queue.py), and the fleet's current
role mix — re-partitioning a fixed worker pool between prefill and
decode the way DistServe picks a goodput-optimal xPyD split and
Splitwise resizes phase pools, but live.

Decision guard rails (every knob is ``DTPU_PLANNER_RECONFIG_<FIELD>``):

- **hysteresis**: a flip direction must be signalled for
  ``hysteresis_intervals`` consecutive planner steps before any
  directive is issued — one noisy window never moves capacity;
- **cooldown**: at least ``cooldown_s`` between issued flips;
- **at-most-one flip in flight fleet-wide**: while any worker reports
  ``draining``/``flipping`` (or an unapplied directive exists), no new
  directive is issued;
- **bounded role mix**: never below ``min_prefill`` prefill-capable or
  ``min_decode`` decode-capable workers.

Fencing: directives are written with the PLANNER's primary lease and an
epoch strictly above every epoch visible in the fleet (worker statuses
and pending directives). A planner that crashes after issuing loses the
directive with its lease; a restarted planner recomputes epochs from
the fleet view, so a stale flip can never apply (llm/reconfig.py
rejects non-increasing epochs typed).
"""

from __future__ import annotations

import dataclasses
import time

from dynamo_tpu.llm.reconfig import (ROLE_ROOT, ROLE_STATUS_ROOT, RoleState,
                                     role_key)
from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("planner.reconfig")

#: Which roles can absorb prefill / decode work (agg does both).
PREFILL_CAPABLE = ("prefill", "agg")
DECODE_CAPABLE = ("decode", "agg")


@dataclasses.dataclass
class ReconfigConfig:
    """Role-flip decision knobs. All plain scalars so the generic
    ``DTPU_PLANNER_RECONFIG_<FIELD>`` env override applies
    (runtime/config.py ``_apply_scalar_env``)."""

    enabled: bool = False
    # Seconds between issued flips, fleet-wide.
    cooldown_s: float = 120.0
    # Consecutive planner steps a flip signal must persist.
    hysteresis_intervals: int = 2
    # Role-mix floors (capable counts: agg counts for both).
    min_prefill: int = 1
    min_decode: int = 1
    # SLO pressure level (SloPressure.level 0..3) at which a failing
    # ttft/itl target argues for moving capacity.
    pressure_level: int = 2
    # Prefill-queue depth that argues for more prefill capacity even
    # without an SLO signal / that must be clear before giving any up.
    queue_depth_high: int = 4
    queue_depth_low: int = 1
    # Drain budget passed on the directive; 0 = worker default
    # (retire_drain_s).
    drain_s: float = 0.0
    # A live worker stuck draining/flipping longer than this stops
    # blocking new decisions (its status still WARNs in doctor.py); 0
    # disables the escape hatch.
    stuck_flip_s: float = 600.0


def apply_reconfig_env(cfg: ReconfigConfig) -> ReconfigConfig:
    """Overlay DTPU_PLANNER_RECONFIG_* env vars onto ``cfg``."""
    from dynamo_tpu.runtime.config import _apply_scalar_env
    _apply_scalar_env("PLANNER_RECONFIG", cfg)
    return cfg


class RoleReconfigurator:
    """One planner's role-flip decision loop.

    ``pressure_fn`` returns the current SloPressure (or None when no SLO
    plane is reachable); ``queue_depth_fn`` returns the prefill queue
    depth (or None). Both are injectable for tests; the planner wires
    defaults from the process-global SLO plane and the coordinator
    queue. ``clock`` is injectable so cooldown is fake-clock testable.
    """

    def __init__(self, client, namespace: str,
                 config: ReconfigConfig | None = None,
                 pressure_fn=None, queue_depth_fn=None,
                 clock=time.monotonic):
        self._client = client
        self.namespace = namespace
        self.cfg = config or ReconfigConfig()
        self._pressure_fn = pressure_fn
        self._queue_depth_fn = queue_depth_fn
        self._clock = clock
        self._last_flip_t: float | None = None
        self._streak = {"to_prefill": 0, "to_decode": 0}
        self._last_decision_ref: str | None = None
        self.issued: list[dict] = []

    # -- fleet view -----------------------------------------------------------
    async def fleet(self) -> list[dict]:
        """Live worker role statuses (lease-bound: dead workers absent)."""
        items = await self._client.kv_get_prefix(
            f"{ROLE_STATUS_ROOT}{self.namespace}/")
        return [it["v"] for it in items if isinstance(it.get("v"), dict)]

    async def pending_directives(self) -> list[dict]:
        items = await self._client.kv_get_prefix(
            f"{ROLE_ROOT}{self.namespace}/")
        out = []
        for it in items:
            v = it.get("v")
            if isinstance(v, dict):
                out.append({"key": it["k"], **v})
        return out

    # -- one decision step ----------------------------------------------------
    async def step(self) -> dict:
        """Observe signals, apply guard rails, maybe issue ONE directive.
        Returns a decision record (always; ``action`` says what happened)."""
        cfg = self.cfg
        pressure = self._pressure_fn() if self._pressure_fn else None
        depth = (await self._maybe_depth()
                 if self._queue_depth_fn else None)
        fleet = await self.fleet()
        directives = await self.pending_directives()
        await self._gc_directives(fleet, directives)
        record: dict = {
            "pool": "reconfig",
            "pressure": pressure.to_wire() if pressure else None,
            "queue_depth": depth,
            "roles": {s["worker"]: s.get("role") for s in fleet},
            "action": "none",
        }
        want = self._signal(pressure, depth)
        for k in self._streak:
            self._streak[k] = self._streak[k] + 1 if want == k else 0
        record["signal"] = want
        record["streaks"] = dict(self._streak)
        if want is None:
            return record
        if self._streak[want] < cfg.hysteresis_intervals:
            record["action"] = "hysteresis"
            return self._journal_decision(record)
        now = self._clock()
        if (self._last_flip_t is not None
                and now - self._last_flip_t < cfg.cooldown_s):
            record["action"] = "cooldown"
            return self._journal_decision(record)
        if self._flip_in_flight(fleet, directives):
            record["action"] = "flip_in_flight"
            return self._journal_decision(record)
        target_role = "prefill" if want == "to_prefill" else "decode"
        candidate = self._candidate(fleet, target_role)
        if candidate is None:
            record["action"] = "bounded"
            return self._journal_decision(record)
        epoch = self._next_epoch(fleet, directives)
        self._journal_decision(dict(record, action="flip",
                                    worker=candidate["worker"],
                                    target_role=target_role, epoch=epoch))
        directive = await self.issue(candidate["worker"], target_role,
                                     epoch, cause=self._last_decision_ref)
        self._last_flip_t = now
        self._streak[want] = 0
        record["action"] = "flip"
        record["directive"] = directive
        return record

    def _journal_decision(self, record: dict) -> dict:
        """Every non-trivial planner decision (including the guard rails
        that SUPPRESSED a flip) lands on the decision plane with its
        input signals — 'why did/didn't the planner act' is answerable
        from the timeline. The flip decision's ref rides the directive
        so the worker's role_flip_requested chains back to it."""
        # NB ``worker=`` is emit()'s origin override — the flip TARGET
        # rides as a plain attr so the decision stays attributed to the
        # planner and its ref can't collide with the worker's own seqs.
        self._last_decision_ref = journal.emit(
            EventKind.PLANNER_DECISION,
            action=record.get("action"), signal=record.get("signal"),
            pressure=record.get("pressure"),
            queue_depth=record.get("queue_depth"),
            roles=record.get("roles"),
            target_worker=record.get("worker"),
            target_role=record.get("target_role"))
        return record

    async def issue(self, worker_hex: str, role: str, epoch: int,
                    issued_by: str = "planner",
                    cause: str | None = None) -> dict:
        """Write one SetRole directive on OUR lease (planner death ->
        lease expiry -> directive key deleted -> stale flip fenced).
        ``cause`` (the planner_decision journal ref) rides the directive
        into the worker's role_flip_* events."""
        directive = {"role": role, "epoch": int(epoch),
                     "issued_by": issued_by, "ts": time.time()}
        if cause is not None:
            directive["cause"] = cause
        if self.cfg.drain_s > 0:
            directive["drain_s"] = self.cfg.drain_s
        await self._client.kv_put(
            role_key(self.namespace, int(worker_hex, 16)), directive,
            use_primary_lease=True)
        self.issued.append({"worker": worker_hex, **directive})
        log.info("issued SetRole %s -> %s (epoch %d)", worker_hex, role,
                 epoch)
        return {"worker": worker_hex, **directive}

    # -- internals ------------------------------------------------------------
    async def _maybe_depth(self):
        try:
            return await self._queue_depth_fn()
        except (ConnectionError, OSError, RuntimeError):
            return None

    def _signal(self, pressure, depth) -> str | None:
        """Which direction (if any) the current signals argue for."""
        cfg = self.cfg
        paging = (pressure is not None
                  and pressure.level >= cfg.pressure_level)
        ttft_hot = paging and "ttft" in pressure.failing
        itl_hot = paging and "itl" in pressure.failing
        deep = depth is not None and depth >= cfg.queue_depth_high
        shallow = depth is None or depth <= cfg.queue_depth_low
        if (ttft_hot or deep) and not itl_hot:
            return "to_prefill"
        if itl_hot and shallow and not ttft_hot:
            return "to_decode"
        return None

    def _flip_in_flight(self, fleet: list[dict],
                        directives: list[dict]) -> bool:
        cfg = self.cfg
        now = time.time()
        by_worker = {s["worker"]: s for s in fleet}
        for s in fleet:
            if s.get("state") in (RoleState.DRAINING, RoleState.FLIPPING):
                if (cfg.stuck_flip_s > 0
                        and now - float(s.get("ts") or now) > cfg.stuck_flip_s):
                    log.warning("ignoring stuck flip on %s (state %s for "
                                ">%.0fs)", s["worker"], s.get("state"),
                                cfg.stuck_flip_s)
                    continue
                return True
        for d in directives:
            worker = d["key"].rsplit("/", 1)[-1]
            status = by_worker.get(worker)
            if status is None:
                continue  # dead worker's directive; _gc_directives reaps it
            if int(d.get("epoch", 0)) > int(status.get("epoch", 0)):
                return True
        return False

    def _candidate(self, fleet: list[dict], target_role: str) -> dict | None:
        """Pick the worker to flip toward ``target_role``, respecting the
        role-mix floors. Prefers the least-loaded serving worker of the
        giving role (fewest in-flight streams drain fastest)."""
        cfg = self.cfg
        source_role = "decode" if target_role == "prefill" else "prefill"
        serving = [s for s in fleet
                   if s.get("state") == RoleState.SERVING
                   and s.get("role") == source_role]
        if not serving:
            return None
        prefill_n = sum(1 for s in fleet
                        if s.get("role") in PREFILL_CAPABLE)
        decode_n = sum(1 for s in fleet if s.get("role") in DECODE_CAPABLE)
        if target_role == "prefill" and decode_n - 1 < cfg.min_decode:
            return None
        if target_role == "decode" and prefill_n - 1 < cfg.min_prefill:
            return None
        return min(serving, key=lambda s: int(s.get("inflight") or 0))

    def _next_epoch(self, fleet: list[dict],
                    directives: list[dict]) -> int:
        top = 0
        for s in fleet:
            top = max(top, int(s.get("epoch") or 0))
        for d in directives:
            top = max(top, int(d.get("epoch") or 0))
        return top + 1

    async def _gc_directives(self, fleet: list[dict],
                             directives: list[dict]) -> None:
        """Reap directives that are applied (worker's epoch caught up) or
        orphaned (worker gone): the directive key is a pending verb, not
        a desired-state record — leaving it would replay the flip into
        every watch reconnect until the issuer dies."""
        by_worker = {s["worker"]: s for s in fleet}
        for d in directives:
            worker = d["key"].rsplit("/", 1)[-1]
            status = by_worker.get(worker)
            applied = (status is not None
                       and int(status.get("epoch") or 0)
                       >= int(d.get("epoch") or 0))
            if status is None or applied:
                try:
                    await self._client.kv_delete(d["key"])
                except (ConnectionError, OSError, RuntimeError):
                    pass
