"""Tokenizer wrapper with incremental (streaming) detokenization.

Capability parity with reference lib/llm/src/tokenizers.rs: Encoder/Decoder
traits over HF ``tokenizers`` (tokenizers.rs:33-300), a ``DecodeStream`` that
emits UTF-8-safe text deltas token by token (tokenizers.rs:214), and a
``Sequence`` accumulating ids+text. Incremental decode keeps prefix/read
offsets so multi-token unicode graphemes and sentencepiece prefix-space
handling produce exact concatenation-equal output.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Sequence as Seq

from tokenizers import Tokenizer as HFTokenizer


class Tokenizer:
    """Thread-safe wrapper over a HF tokenizers.Tokenizer."""

    def __init__(self, hf: HFTokenizer):
        self._hf = hf
        self._lock = threading.Lock()
        # Explicit EOS ids (e.g. from GGUF metadata) override the
        # name-convention discovery in eos_token_ids().
        self.eos_override: list[int] | None = None

    @classmethod
    def from_file(cls, path: str) -> "Tokenizer":
        if path.endswith(".gguf"):
            from dynamo_tpu.llm.gguf import tokenizer_from_gguf
            return tokenizer_from_gguf(path)
        return cls(HFTokenizer.from_file(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Tokenizer":
        return cls(HFTokenizer.from_str(blob.decode("utf-8")))

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "Tokenizer":
        """Load from a local model directory containing tokenizer.json."""
        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no tokenizer.json under {model_dir}")
        return cls.from_file(path)

    def to_bytes(self) -> bytes:
        return self._hf.to_str().encode("utf-8")

    @property
    def vocab_size(self) -> int:
        return self._hf.get_vocab_size()

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        with self._lock:
            return self._hf.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Seq[int], skip_special_tokens: bool = True) -> str:
        with self._lock:
            return self._hf.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> int | None:
        return self._hf.token_to_id(token)

    def eos_token_ids(self) -> list[int]:
        """Best-effort EOS discovery from common conventions."""
        if self.eos_override is not None:
            return list(self.eos_override)
        ids = []
        for tok in ("</s>", "<|endoftext|>", "<|eot_id|>", "<|end_of_text|>",
                    "<|im_end|>", "<eos>"):
            tid = self._hf.token_to_id(tok)
            if tid is not None:
                ids.append(tid)
        return ids


class DecodeStream:
    """Incremental detokenizer (reference tokenizers.rs DecodeStream :214).

    ``step(token_id)`` returns the new text produced by appending the token, or
    None when the bytes so far don't yet form valid complete text (e.g. half of
    a multi-byte grapheme). The offsets approach matches HF's streaming decode:
    decode(all_ids[prefix:]) vs decode(all_ids[prefix:read]) and emit the
    suffix only when it's complete and doesn't end in a replacement char.
    """

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip = skip_special_tokens
        self.ids: list[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_id: int) -> str | None:
        self.ids.append(token_id)
        prefix_text = self._tok.decode(self.ids[self._prefix_offset:self._read_offset],
                                       self._skip)
        new_text = self._tok.decode(self.ids[self._prefix_offset:], self._skip)
        if new_text.endswith("�"):
            # Incomplete UTF-8 sequence: wait for more tokens.
            return None
        if len(new_text) <= len(prefix_text):
            return None
        delta = new_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self.ids)
        return delta


class StopSequenceChecker:
    """Streaming stop-string detection over appended text deltas.

    Holds back a tail of ``max_stop_len - 1`` chars so a stop string split
    across deltas is still caught (reference backend.rs stop-sequence
    handling). ``append`` returns (emit_text, matched) where emit_text is the
    safe-to-emit portion.
    """

    def __init__(self, stops: list[str]):
        self.stops = [s for s in stops if s]
        self._held = ""
        self._max = max((len(s) for s in self.stops), default=0)

    def append(self, delta: str) -> tuple[str, bool]:
        if not self.stops:
            return delta, False
        buf = self._held + delta
        # Earliest match across all stop strings wins, so no text past an
        # earlier stop leaks when a later-listed stop also matches.
        best = -1
        for stop in self.stops:
            idx = buf.find(stop)
            if idx != -1 and (best == -1 or idx < best):
                best = idx
        if best != -1:
            self._held = ""
            return buf[:best], True
        keep = min(self._max - 1, len(buf))
        # Only hold back a tail that is a prefix of some stop string.
        hold = 0
        for k in range(keep, 0, -1):
            tail = buf[-k:]
            if any(s.startswith(tail) for s in self.stops):
                hold = k
                break
        self._held = buf[len(buf) - hold:] if hold else ""
        emit = buf[:len(buf) - hold] if hold else buf
        return emit, False

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held


def make_test_tokenizer(vocab_texts: list[str] | None = None) -> Tokenizer:
    """Build a small self-contained byte-level BPE tokenizer (no hub access).
    Used by tests and the mocker; NOT for real models."""
    from tokenizers import models, pre_tokenizers, decoders, trainers

    hf = HFTokenizer(models.BPE(unk_token=None))
    hf.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    hf.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=512, special_tokens=["<|endoftext|>", "<|im_end|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    corpus = vocab_texts or [
        "hello world this is a test of the tpu native serving framework",
        "the quick brown fox jumps over the lazy dog 0123456789",
        "def main(): return [i for i in range(10)]",
    ]
    hf.train_from_iterator(corpus, trainer)
    return Tokenizer(hf)
