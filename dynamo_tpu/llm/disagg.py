"""Disaggregated prefill/decode serving: worker-side handlers + config.

TPU-native version of the reference's disaggregation path (SURVEY.md call
stack 3.3; components/backends/vllm/src/dynamo/vllm/handlers.py:113-199):

- The PREFILL worker serves a prefill-only endpoint: it computes the
  prompt's KV (engine.prefill_extract on the engine thread), samples the
  first token, and streams the KV back as a chunked parcel
  (llm/kv_transfer.py) — the host-staged stand-in for the reference's NIXL
  GPU->GPU writes (handlers.py:167-199 PrefillWorkerHandler).
- The DECODE worker conditionally forwards prompts longer than
  ``max_local_prefill_length`` to a discovered prefill worker
  (round-robin, like the reference's prefill_worker_client.round_robin at
  handlers.py:148-152), assembles the parcel, uploads it into its own KV
  pool (the mesh re-shards on upload, so TP-mismatched transfers work),
  and decodes from the returned first token. Anything shorter — or any
  remote failure — prefills locally (conditional disaggregation,
  lib/llm/src/disagg_router.rs:25-45).

The conditional threshold is dynamic: ``DisaggRouterConfig`` reads
``disagg/<model>`` from the coordinator KV store and watches it for
updates, mirroring DisaggRouterConf::from_etcd_with_watcher.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import AsyncIterator

from dynamo_tpu.llm.kv_transfer import collect_prefill_response, kv_to_chunks
from dynamo_tpu.llm.model_card import model_slug
from dynamo_tpu.llm.protocols import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.errors import (
    EngineError, NoInstancesError, StreamIncompleteError)
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import span

log = get_logger("disagg")

DISAGG_CONFIG_ROOT = "disagg/"

# Default component name prefill workers serve under (decode workers
# discover them by this, namespaced like any endpoint).
PREFILL_COMPONENT = "prefill"
PREFILL_ENDPOINT = "generate"


def disagg_config_key(model_name: str) -> str:
    return f"{DISAGG_CONFIG_ROOT}{model_slug(model_name)}"


class DisaggRouterConfig:
    """Per-model conditional-disaggregation config, watchable from the
    coordinator KV store (reference DisaggRouterConf,
    disagg_router.rs:25-45: read once, then watched for updates)."""

    def __init__(self, max_local_prefill_length: int = 512):
        self.max_local_prefill_length = max_local_prefill_length
        self._watch = None
        self._client = None
        self._key: str | None = None
        self._task: asyncio.Task | None = None
        # Observable recovery count (tests + debugging): how many times
        # the watch loop survived a failure and re-established itself.
        self.watch_restarts = 0

    def prefill_remote(self, prompt_len: int) -> bool:
        return prompt_len > self.max_local_prefill_length

    @classmethod
    async def from_coordinator_with_watch(
            cls, client, model_name: str,
            default_max_local: int = 512) -> "DisaggRouterConfig":
        cfg = cls(default_max_local)
        cfg._client = client
        cfg._key = disagg_config_key(model_name)
        watch = await client.watch_prefix(cfg._key)
        for item in watch.snapshot:
            cfg._apply(item["v"])
        cfg._watch = watch
        cfg._task = asyncio.create_task(cfg._watch_loop())
        return cfg

    def _apply(self, value) -> None:
        if isinstance(value, dict) and "max_local_prefill_length" in value:
            self.max_local_prefill_length = int(
                value["max_local_prefill_length"])
            log.info("disagg config updated: max_local_prefill_length=%d",
                     self.max_local_prefill_length)

    async def _watch_loop(self) -> None:
        """Apply config puts until cancelled. Must never die silently: a
        dead watch freezes the conditional-disagg threshold at its last
        value for the life of the worker — so any failure (a malformed
        value raising in _apply, a watch lost to a coordinator restart
        the client could not replay) re-establishes the watch under the
        unified retry policy (runtime/retry.py) instead of returning."""
        from dynamo_tpu.runtime.retry import Backoff, policies
        backoff = Backoff(policies.COORD_RECONNECT)
        while True:
            try:
                async for event in self._watch:
                    if event["event"] != "put":
                        continue
                    try:
                        self._apply(event["value"])
                    except (TypeError, ValueError):
                        log.warning("malformed disagg config ignored: %r",
                                    event["value"])
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — survive, re-watch
                log.exception("disagg config watch failed; re-watching")
            await backoff.sleep()
            try:
                self._watch = await self._client.watch_prefix(self._key)
                for item in self._watch.snapshot:
                    try:
                        self._apply(item["v"])
                    except (TypeError, ValueError):
                        log.warning("malformed disagg config ignored: %r",
                                    item["v"])
                self.watch_restarts += 1
                backoff.reset()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("disagg config re-watch failed; will retry")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
        if self._watch:
            await self._watch.cancel()


def make_prefill_handler(engine, plane=None):
    """Prefill-worker endpoint handler: prompt in, (KV + first token) out.

    With ``plane`` (a KvPlaneServer): the parcel is STAGED on the direct
    KV data plane and the response carries only a small transfer ticket —
    the decode worker pulls the bulk bytes worker-to-worker
    (llm/kv_plane.py, the NIXL role). Without it: the v0 inline-chunk
    contract (one meta frame {shape, dtype, n_chunks}, n_chunks kv_chunk
    frames, then the first token — the role of the reference's
    kv_transfer_params response, handlers.py:195-199)."""

    supports_streaming = "on_ticket" in getattr(
        inspect.signature(engine.prefill_extract_staged), "parameters", {}) \
        if hasattr(engine, "prefill_extract_staged") else False

    async def handle(request, context: Context) -> AsyncIterator[dict]:
        if isinstance(request, dict) and request.get("clear_kv_blocks"):
            yield {"cleared": await engine.clear_kv_blocks()}
            return
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        phase = getattr(engine, "phase", None)  # tracing.PhaseMetrics
        if plane is not None:
            # Chunk-streamed extract (engine._prefill_extract_streamed):
            # the engine stages the ticket BEFORE prefilling and delivers
            # it via on_ticket — yield it to the decode worker right
            # away so its plane pull overlaps the remaining chunks; the
            # first token follows when the job completes. Engines
            # without the on_ticket parameter (scripted test engines,
            # older queue workers) keep the stage-after-prefill order.
            loop = asyncio.get_running_loop()
            ticket_fut: asyncio.Future = loop.create_future()
            staged: list[dict] = []  # the delivered ticket, loop-side

            def _deliver(t: dict) -> None:
                staged.append(t)
                if not ticket_fut.done():
                    ticket_fut.set_result(True)

            def on_ticket(t: dict) -> None:
                loop.call_soon_threadsafe(_deliver, t)

            with span("kv.transfer.send", ctx=context, path="plane") as sp:
                t0 = time.monotonic()
                if supports_streaming:
                    job = asyncio.ensure_future(engine.run_job(
                        lambda: engine.prefill_extract_staged(
                            req, plane, on_ticket=on_ticket)))
                else:
                    job = asyncio.ensure_future(engine.run_job(
                        lambda: engine.prefill_extract_staged(req, plane)))
                await asyncio.wait({job, ticket_fut},
                                   return_when=asyncio.FIRST_COMPLETED)
                streamed = bool(staged) and not job.done()
                if streamed:
                    # Ticket ahead of the first token: ship it now.
                    yield LLMEngineOutput(disagg_params={
                        "ticket": staged[0]}).to_wire()
                first_token, ticket, prompt_len = await job
                sp.set(nbytes=int(ticket.get("nbytes", 0)),
                       prompt_tokens=prompt_len, streamed=streamed)
                if phase is not None:
                    phase.kv_transfer.observe(time.monotonic() - t0,
                                              direction="send")
                    phase.kv_transfer_bytes.observe(
                        ticket.get("nbytes", 0), direction="send")
            log.info("prefill parcel staged%s: %d tokens, ticket %d",
                     " (chunk-streamed)" if streamed else "",
                     prompt_len, ticket["id"])
            if not streamed:
                yield LLMEngineOutput(
                    disagg_params={"ticket": ticket}).to_wire()
            yield LLMEngineOutput(token_ids=[first_token]).to_wire()
            return
        with span("kv.transfer.send", ctx=context, path="inline") as sp:
            t0 = time.monotonic()
            first_token, kv, prompt_len = await engine.run_job(
                lambda: engine.prefill_extract(req))
            meta, chunks = kv_to_chunks(kv)
            meta["prompt_len"] = prompt_len
            sp.set(nbytes=int(kv.nbytes), chunks=len(chunks),
                   prompt_tokens=prompt_len)
            yield LLMEngineOutput(disagg_params=meta).to_wire()
            for chunk in chunks:
                if context.is_killed or context.is_stopped:
                    return
                yield LLMEngineOutput(
                    disagg_params={"kv_chunk": chunk}).to_wire()
            if phase is not None:
                phase.kv_transfer.observe(time.monotonic() - t0, direction="send")
                phase.kv_transfer_bytes.observe(kv.nbytes,
                                                direction="send")
        yield LLMEngineOutput(token_ids=[first_token]).to_wire()

    return handle


class DisaggDecodeHandler:
    """Decode-worker handler with conditional remote prefill (reference
    DecodeWorkerHandler, handlers.py:113-162)."""

    def __init__(self, engine, prefill_client, config: DisaggRouterConfig,
                 plane_client=None, queue_dispatcher=None):
        self.engine = engine
        self.prefill_client = prefill_client
        self.config = config
        # Pull side of the direct KV data plane (created on demand: a
        # plane-less prefill worker just sends inline chunks instead).
        if plane_client is None:
            from dynamo_tpu.llm.kv_plane import KvPlaneClient
            plane_client = KvPlaneClient()
        self.plane_client = plane_client
        # Queue-based dispatch (llm/prefill_queue.py): when set, remote
        # prefills go through the shared coordinator queue with depth
        # backpressure instead of direct round-robin.
        self.queue_dispatcher = queue_dispatcher
        # Telemetry for tests + metrics.
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_failures = 0

    def handler(self):
        async def handle(request, context):
            if isinstance(request, dict) and request.get("clear_kv_blocks"):
                # Clear our own pool AND fan out to the prefill workers
                # this decode worker fronts (the frontend only discovers
                # decode endpoints).
                freed = await self.engine.clear_kv_blocks()
                for iid in self.prefill_client.instance_ids():
                    try:
                        stream = await self.prefill_client.direct(
                            {"clear_kv_blocks": True}, iid)
                        async for item in stream:
                            freed += item.get("cleared", 0)
                    except Exception:  # noqa: BLE001 — best-effort admin
                        log.warning("clear_kv_blocks failed on prefill %x",
                                    iid, exc_info=True)
                yield {"cleared": freed}
                return
            if isinstance(request, dict) and request.get("embed"):
                # Embeddings don't involve the disagg path: serve locally.
                vectors = await self.engine.embed(
                    request["token_lists"], request.get("pooling", "last"))
                yield {"embeddings": vectors}
                return
            async for out in self.generate(request, context):
                yield out
        return handle

    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        # LoRA adapter requests always prefill locally: the prefill
        # worker holds base weights only, and base-computed KV under an
        # adapter-salted hash chain would be silently wrong KV.
        if self.config.prefill_remote(len(req.token_ids)) \
                and not getattr(req, "adapter", None):
            injected = await self._remote_prefill(req, context)
            if injected is not None:
                self.remote_prefills += 1
                first_token, kv = injected
                log.info("remote prefill injected: %d tokens",
                         len(req.token_ids))
                async for out in self.engine.generate_injected(
                        req, context, first_token, kv):
                    yield out
                return
        self.local_prefills += 1
        async for out in self.engine.generate(req, context):
            yield out

    async def _remote_prefill(self, req: PreprocessedRequest,
                              context: Context):
        """Forward the prompt to a prefill worker (direct round-robin, or
        the shared queue when a dispatcher is configured); returns
        (first_token, kv parcel) or None to fall back to local prefill
        (any remote failure degrades to aggregated serving, never fails
        the request)."""
        try:
            if self.queue_dispatcher is not None:
                return await self.queue_dispatcher.remote_prefill(
                    req, context=context)
            stream = await self.prefill_client.round_robin(
                req.to_wire(), context=context)
            return await collect_prefill_response(
                stream, plane_client=self.plane_client,
                metrics=getattr(self.engine, "phase", None))
        except (NoInstancesError, StreamIncompleteError, EngineError,
                ConnectionError, OSError, RuntimeError) as exc:
            self.remote_failures += 1
            log.warning("remote prefill failed (%s: %s); prefilling locally",
                        type(exc).__name__, exc)
            return None
