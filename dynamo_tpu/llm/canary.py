"""Synthetic canary prober: active verification of the serving path.

Everything else in the observability stack is passive — a wedged worker
is discovered when USER traffic hits it. The canary closes that hole: a
low-rate background loop sends tiny known-answer greedy requests pinned
per worker (``direct`` routing, the same per-worker selection machinery
admin ops use), checks token-exact output and TTFT against bounds, and
feeds the breaker board — so a sick worker is ejected from selection
*before* user traffic reaches it, and (because ``direct`` bypasses
breaker filtering) keeps being probed while open, closing the breaker
the moment it recovers.

Known-answer: greedy decoding is deterministic, so the first successful
probe's tokens become the model's reference output; every later probe
must match token-exactly (a worker emitting different greedy tokens is
corrupt — wrong weights, bad KV reuse — not just slow).

Canaries are **admission-exempt and invisible to user-facing SLIs**:
they ride the request plane directly, below the HTTP ingress — no
AdaptiveLimiter permit, no SLO ``observe_*`` calls, no RequestLedger
record — so synthetic traffic can never page an operator about itself
or pollute per-tenant accounting.

Decision plane: every failed probe journals a ``canary_fail`` (chained
to the previous failure on the same worker), recovery journals a
``canary_ok`` chained to the failure streak, and the breaker transition
the canary causes carries the probe's ref as its explicit ``cause``.

Exports ``canary_probes_total{worker,outcome}`` and
``canary_ttft_seconds{worker}``. Knobs: ``[canary]`` TOML table /
``DTPU_CANARY_<FIELD>`` env / ``--canary*`` frontend flags.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("canary")

#: Probe outcomes (the canary_probes_total label vocabulary).
OUTCOMES = ("ok", "timeout", "error", "mismatch", "slow_ttft")


@dataclasses.dataclass
class CanaryConfig:
    """All plain scalars so the generic ``DTPU_CANARY_<FIELD>`` env
    override in runtime/config.py maps 1:1."""

    enabled: bool = False
    # Seconds between probe sweeps (every worker of every model is
    # probed once per sweep — keep this >> a probe's service time).
    interval_s: float = 15.0
    # The known-answer request: tiny and greedy.
    prompt: str = "The quick brown fox"
    max_tokens: int = 4
    # A first token slower than this fails the probe ("slow_ttft").
    ttft_bound_ms: float = 5000.0
    # Whole-probe deadline; a worker that answers nothing within it is
    # wedged ("timeout") — this is what catches the hung-but-leased
    # worker user traffic would otherwise discover.
    timeout_s: float = 10.0
    # Canary-gated join (autoscaling, docs/RESILIENCE.md
    # "Autoscaling"): a worker that joins holds its breaker on
    # PROBATION — no user traffic at all — until a probe chain passes;
    # the releasing canary_ok is caused by the worker_join event so
    # the admission is one walkable chain on /debug/timeline.
    gate_joins: bool = False
    # How many consecutive ok probes release a probation hold.
    gate_probes: int = 1


def apply_canary_env(cfg: CanaryConfig) -> CanaryConfig:
    """Overlay DTPU_CANARY_* env vars onto ``cfg`` (same mechanism as
    the planner's ReconfigConfig)."""
    from dynamo_tpu.runtime.config import _apply_scalar_env
    _apply_scalar_env("CANARY", cfg)
    return cfg


class CanaryProber:
    """One frontend's canary loop over every served model's workers."""

    def __init__(self, manager, config: CanaryConfig | None = None,
                 metrics=None):
        self.manager = manager
        self.cfg = config or CanaryConfig()
        self._task: asyncio.Task | None = None
        # model -> reference greedy tokens (set by the first ok probe).
        self._expected: dict[str, list[int]] = {}
        # worker id -> consecutive failures / last fail ref / stats.
        self._fails: dict[int, int] = {}
        self._fail_refs: dict[int, str] = {}
        self._stats: dict[int, dict] = {}
        # Canary-gated joins: worker id -> {"join_ref", "ok_streak"}.
        # Membership means the worker's breaker is held on probation.
        self._probation: dict[int, dict] = {}
        self._gate_tasks: set[asyncio.Task] = set()
        self.sweeps = 0
        self._m_probes = self._m_ttft = None
        if metrics is not None:
            m = metrics.namespace("canary")
            self._m_probes = m.counter(
                "canary_probes_total",
                "Synthetic canary probes by worker and outcome",
                ["worker", "outcome"])
            self._m_ttft = m.gauge(
                "canary_ttft_seconds",
                "Latest canary probe TTFT per worker", ["worker"])

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())
            log.info("canary prober armed (every %.1fs, %d tokens, "
                     "ttft bound %.0f ms)", self.cfg.interval_s,
                     self.cfg.max_tokens, self.cfg.ttft_bound_ms)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            try:
                await self.sweep()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — probing must never die
                log.exception("canary sweep failed")

    # -- join gating (discovery hooks) ----------------------------------------
    def note_join(self, served, iid: int) -> None:
        """Discovery worker_join hook: with ``gate_joins`` on, hold the
        worker's breaker (probation — routers exclude it, half-open
        probes included) and probe it IMMEDIATELY instead of waiting
        out the sweep interval. The probe that passes releases the
        hold; until then no user request can reach the worker."""
        if not self.cfg.gate_joins or iid in self._probation:
            return
        join_ref = journal.recent_ref(EventKind.WORKER_JOIN)
        self._probation[iid] = {"join_ref": join_ref, "ok_streak": 0}
        served.client.breakers.hold(iid, cause=join_ref)
        log.info("canary: worker %x joined on probation; probing now", iid)
        task = asyncio.get_running_loop().create_task(
            self._gate_probe(served, iid))
        self._gate_tasks.add(task)
        task.add_done_callback(self._gate_tasks.discard)

    def note_leave(self, served, iid: int) -> None:
        """Discovery worker_leave hook: forget the worker's probe state
        (a rejoining worker starts a fresh probation, not an inherited
        failure streak)."""
        self._probation.pop(iid, None)
        self._fails.pop(iid, None)
        self._fail_refs.pop(iid, None)

    async def _gate_probe(self, served, iid: int) -> None:
        try:
            await self.probe(served, iid)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the sweep loop retries
            log.exception("canary join probe failed")

    async def sweep(self) -> int:
        """Probe every worker of every remotely-served model once.
        Returns the number of probes issued."""
        probes = 0
        for name, served in list(self.manager.models.items()):
            if served.client is None:
                continue  # in-process pipeline: nothing to eject
            for iid in served.client.instance_ids():
                await self.probe(served, iid)
                probes += 1
        self.sweeps += 1
        return probes

    # -- one probe ------------------------------------------------------------
    def _build_request(self, served):
        from dynamo_tpu.llm.protocols import PreprocessedRequest
        tokenizer = served.preprocessor.tokenizer
        req = PreprocessedRequest(
            model=served.entry.model_name,
            token_ids=tokenizer.encode(self.cfg.prompt))
        req.stop_conditions.max_tokens = self.cfg.max_tokens
        # Exact-token determinism: the reference output must not depend
        # on where an eos happens to land.
        req.stop_conditions.ignore_eos = True
        req.sampling_options.temperature = 0.0
        return req

    async def probe(self, served, iid: int) -> str:
        """One pinned probe; returns the outcome. Pins with ``direct``
        routing, which deliberately bypasses breaker filtering — an
        ejected worker keeps being probed, and the probe that succeeds
        is what re-admits it."""
        cfg = self.cfg
        model = served.entry.model_name
        ctx = Context()
        t0 = time.monotonic()
        ttft: float | None = None
        tokens: list[int] = []

        async def consume() -> None:
            nonlocal ttft
            stream = await served.client.direct(
                self._build_request(served).to_wire(), iid, context=ctx)
            async for out in stream:
                if not isinstance(out, dict):
                    continue
                if out.get("token_ids"):
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    tokens.extend(out["token_ids"])
                if out.get("finish_reason"):
                    break

        outcome = "ok"
        detail: dict = {}
        try:
            await asyncio.wait_for(consume(), cfg.timeout_s)
        except asyncio.TimeoutError:
            ctx.kill()  # free the worker-side slot if it ever wakes up
            outcome = "timeout"
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — any failure is the signal
            outcome = "error"
            detail["error"] = f"{type(exc).__name__}: {exc}"
        else:
            expected = self._expected.get(model)
            if not tokens:
                outcome = "error"
                detail["error"] = "empty stream"
            elif expected is not None and tokens != expected:
                outcome = "mismatch"
                detail["expected"] = expected[:8]
                detail["got"] = tokens[:8]
            elif ttft is not None and ttft * 1000.0 > cfg.ttft_bound_ms:
                outcome = "slow_ttft"
                detail["ttft_ms"] = round(ttft * 1000.0, 1)
            else:
                self._expected.setdefault(model, tokens)
        self._note(served, iid, model, outcome, ttft, detail)
        return outcome

    def _note(self, served, iid: int, model: str, outcome: str,
              ttft: float | None, detail: dict) -> None:
        worker = f"{iid:x}"
        if self._m_probes is not None:
            self._m_probes.inc(worker=worker, outcome=outcome)
        if ttft is not None and self._m_ttft is not None:
            self._m_ttft.set(ttft, worker=worker)
        stat = self._stats.setdefault(iid, {"probes": 0, "ok": 0, "fail": 0})
        stat["probes"] += 1
        stat["last_outcome"] = outcome
        stat["last_ttft_s"] = ttft
        board = served.client.breakers
        if outcome == "ok":
            stat["ok"] += 1
            streak, ref = self._fails.pop(iid, 0), self._fail_refs.pop(
                iid, None)
            stat["consecutive_fails"] = 0
            ok_ref = None
            if streak:
                ok_ref = journal.emit(
                    EventKind.CANARY_OK, cause=ref, worker_id=worker,
                    model=model, recovered_after=streak)
                log.info("canary: worker %s recovered after %d failures",
                         worker, streak)
            gate = self._probation.get(iid)
            if gate is not None:
                gate["ok_streak"] += 1
                if gate["ok_streak"] < max(1, self.cfg.gate_probes):
                    return  # probation holds until the chain completes
                self._probation.pop(iid, None)
                if ok_ref is None:
                    # The admitting event: caused by the join that put
                    # the worker on probation — the last link of the
                    # scale-out chain on /debug/timeline.
                    ok_ref = journal.emit(
                        EventKind.CANARY_OK,
                        cause=ref or gate["join_ref"], worker_id=worker,
                        model=model, admitted=True,
                        probes=gate["ok_streak"])
                log.info("canary: worker %s passed join probation; "
                         "admitting", worker)
            # Only a recovering/held breaker gets the success signal:
            # steady canary TTFTs must not pollute the breaker's latency
            # EWMA (a tiny probe is far faster than real traffic).
            from dynamo_tpu.runtime.overload import CLOSED
            if board.state(iid) != CLOSED:
                board.record_success(iid, ttft, cause=ok_ref)
            return
        stat["fail"] += 1
        self._fails[iid] = self._fails.get(iid, 0) + 1
        stat["consecutive_fails"] = self._fails[iid]
        ref = journal.emit(
            EventKind.CANARY_FAIL, cause=self._fail_refs.get(iid),
            worker_id=worker, model=model, outcome=outcome,
            consecutive=self._fails[iid], **detail)
        self._fail_refs[iid] = ref
        log.warning("canary: worker %s probe failed (%s, %d consecutive)",
                    worker, outcome, self._fails[iid])
        # The breaker transition this causes names the probe explicitly.
        board.record_failure(iid, cause=ref)

    # -- operator surface ------------------------------------------------------
    def status(self) -> dict:
        return {
            "enabled": True,
            "interval_s": self.cfg.interval_s,
            "gate_joins": self.cfg.gate_joins,
            "probation": sorted(f"{iid:x}" for iid in self._probation),
            "sweeps": self.sweeps,
            "workers": {f"{iid:x}": dict(stat)
                        for iid, stat in sorted(self._stats.items())},
        }
