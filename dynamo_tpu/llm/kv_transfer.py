"""KV parcel serialization for disaggregated prefill->decode transfer.

The host-staged v0 data plane (SURVEY.md §5.8): the prefill worker extracts
the prompt's KV pages ([2, L, Nkv, n_pages, page, D] bf16), serializes them,
and streams them INLINE over the request plane as chunked response frames —
the role NIXL RDMA plays in the reference (lib/llm/src/block_manager/storage/
nixl.rs; vllm handlers.py kv_transfer_params). A device-to-device ICI path
(jax.experimental.transfer) can replace the wire format transparently later:
the metadata contract (shape + dtype + chunk count) stays.

TP-mismatch handling: the parcel is the FULL unsharded KV — the decode
worker's mesh re-shards on upload (runner.insert_pages), so 1-TP prefill ->
2-TP decode works without the reference's block_copy.cu transpose kernel.
"""

from __future__ import annotations

import time
from typing import AsyncIterator

import numpy as np

from dynamo_tpu.runtime.tracing import span

CHUNK_BYTES = 8 << 20  # 8 MiB response frames

_DTYPES = {"bfloat16": None, "float32": np.float32, "float16": np.float16}


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def kv_to_chunks(kv: np.ndarray) -> tuple[dict, list[bytes]]:
    """Serialize a KV parcel: returns (meta, chunk list)."""
    raw = np.ascontiguousarray(kv).tobytes()
    chunks = [raw[i:i + CHUNK_BYTES] for i in range(0, len(raw), CHUNK_BYTES)]
    if not chunks:
        chunks = [b""]
    meta = {"shape": list(kv.shape), "dtype": str(kv.dtype),
            "n_chunks": len(chunks)}
    return meta, chunks


def kv_from_chunks(meta: dict, chunks: list[bytes]) -> np.ndarray:
    assert len(chunks) == meta["n_chunks"], (len(chunks), meta)
    dtype = (_bf16() if meta["dtype"] == "bfloat16"
             else np.dtype(meta["dtype"]))
    raw = b"".join(chunks)
    return np.frombuffer(raw, dtype=dtype).reshape(meta["shape"])


async def collect_prefill_response(stream: AsyncIterator[dict],
                                   plane_client=None,
                                   metrics=None) -> tuple[int, np.ndarray]:
    """Assemble a prefill worker's response into (first_token, kv parcel).

    Two wire forms: a transfer TICKET (the worker staged the parcel on
    the direct KV data plane, llm/kv_plane.py — pull the bulk bytes
    there), or inline chunks (the v0 host-staged path, still emitted by
    plane-less workers). ``metrics`` (a tracing.PhaseMetrics) feeds the
    kv_transfer_seconds/bytes histograms; the recv span records either
    way."""
    import asyncio

    t0 = time.monotonic()
    with span("kv.transfer.recv") as sp:
        chunks: list[bytes] = []
        meta = None
        ticket = None
        first_token = None
        pull_task: asyncio.Task | None = None
        try:
            async for out in stream:
                dp = out.get("disagg_params") or {}
                if "ticket" in dp and pull_task is None \
                        and plane_client is not None:
                    # Start pulling the MOMENT the ticket lands: with a
                    # chunk-streamed prefill worker the ticket precedes
                    # the first token, so the bulk KV bytes cross the
                    # wire while the remaining chunks still compute —
                    # the transfer tax hides behind prefill instead of
                    # serializing after it.
                    ticket = dp["ticket"]
                    pull_task = asyncio.ensure_future(
                        plane_client.pull(ticket))
                elif "ticket" in dp:
                    ticket = dp["ticket"]
                if "kv_chunk" in dp:
                    chunks.append(dp["kv_chunk"])
                if "shape" in dp:
                    meta = dp
                toks = out.get("token_ids") or []
                if toks:
                    first_token = toks[0]
        except BaseException:
            # The stream died with a pull in flight (prefill aborted
            # mid-chunk): don't leak the executor-backed task.
            if pull_task is not None:
                pull_task.cancel()
            raise
        if first_token is None or (meta is None and ticket is None):
            if pull_task is not None:
                pull_task.cancel()
            raise RuntimeError("incomplete disaggregated prefill response")
        if ticket is not None:
            if plane_client is None:
                raise RuntimeError(
                    "prefill worker sent a KV-plane ticket but this worker "
                    "has no plane client")
            kv = await pull_task
            sp.set(path="plane", nbytes=int(kv.nbytes))
        else:
            kv = kv_from_chunks(meta, chunks)
            sp.set(path="inline", nbytes=int(kv.nbytes),
                   chunks=len(chunks))
    if metrics is not None:
        metrics.kv_transfer.observe(time.monotonic() - t0,
                                    direction="recv")
        metrics.kv_transfer_bytes.observe(kv.nbytes, direction="recv")
    return first_token, kv
