"""Frontend timeline collector: the fleet's merged decision plane.

Workers (and the planner) publish journal deltas on the namespace's
journal subject (``runtime/journal.py JournalPublisher``); this
collector subscribes, feeds ``FleetTimeline`` (seq-fenced merge with
restart/overflow ``journal_gap`` marking and ApproxKvIndexer-style
staleness pruning), and serves the result — merged with the frontend's
OWN process journal, where sheds/breaker/SLO/migration events are
emitted — as the ``GET /debug/timeline`` payload
(docs/OBSERVABILITY.md "Decision plane").
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.runtime import journal as journal_mod
from dynamo_tpu.runtime.journal import (FleetTimeline, journal_subject,
                                        merge_timeline)
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("timeline")

#: Stream fences for workers that stop publishing are pruned after this
#: long (the lease TTL bounds real death detection; this only bounds
#: fence memory — merged history is kept).
DEFAULT_TTL_S = 60.0


class TimelineCollector:
    def __init__(self, runtime, namespace: str | None = None,
                 ttl_s: float = DEFAULT_TTL_S):
        self._runtime = runtime
        self.namespace = namespace or runtime.config.namespace
        self.fleet = FleetTimeline(ttl_s=ttl_s)
        self._sub = None
        self._task: asyncio.Task | None = None
        self._prune_task: asyncio.Task | None = None

    async def start(self) -> None:
        client = self._runtime.require_coordinator()
        self._sub = await client.subscribe(journal_subject(self.namespace))
        self._task = asyncio.create_task(self._loop())
        self._prune_task = asyncio.create_task(self._prune_loop())

    async def stop(self) -> None:
        for task in (self._task, self._prune_task):
            if task is not None:
                task.cancel()
        self._task = self._prune_task = None
        if self._sub is not None:
            await self._sub.cancel()
            self._sub = None

    async def _loop(self) -> None:
        async for msg in self._sub:
            try:
                self.fleet.apply_delta(msg["payload"])
            except Exception:  # noqa: BLE001 — one bad delta, keep merging
                log.exception("bad journal delta")

    async def _prune_loop(self) -> None:
        while True:
            await asyncio.sleep(self.fleet.ttl_s / 2)
            try:
                dead = self.fleet.prune()
                if dead:
                    log.info("pruned journal stream fences: %s",
                             ", ".join(dead))
            except Exception:  # noqa: BLE001 — maintenance only
                log.exception("timeline prune failed")

    # -- /debug/timeline provider ---------------------------------------------
    def timeline_status(self, limit: int = 512) -> dict:
        """The merged fleet timeline + this process's own journal, one
        causally ordered stream."""
        local = journal_mod.get_journal()
        snap = self.fleet.snapshot(limit=0)
        events = merge_timeline(snap.pop("events"), local, limit=limit)
        return {
            "role": "frontend",
            "local": {"worker": local.worker, "boot": local.boot,
                      "seq": local.seq,
                      "emitted_total": local.emitted_total,
                      "dropped_overflow": local.dropped_overflow},
            **snap,
            "events": events,
        }
