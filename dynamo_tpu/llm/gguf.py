"""GGUF metadata + tokenizer loading.

Capability parity with the reference's GGUF support (its tokenizer layer
reads GGUF checkpoints for the llama.cpp engine path): parse the GGUF v2/v3
container's metadata key-values (no tensor data needed) and rebuild a HF
``tokenizers`` BPE tokenizer from ``tokenizer.ggml.tokens`` +
``tokenizer.ggml.merges`` (gpt2-style byte-level BPE, the format GGUF chat
models ship). The parser is self-contained — GGUF is a simple
little-endian TLV container (spec: github.com/ggerganov/ggml/docs/gguf.md).
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO

GGUF_MAGIC = b"GGUF"

# Metadata value type ids (gguf spec).
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = (
    6, 7, 8, 9, 10, 11, 12)

_SCALAR_FMT = {_T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
               _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
               _T_I64: "<q", _T_F64: "<d"}


def _read(fh: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = fh.read(size)
    if len(data) != size:
        raise ValueError("truncated GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(fh: BinaryIO) -> str:
    n = _read(fh, "<Q")
    return fh.read(n).decode("utf-8", "replace")


def _read_value(fh: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        return _read(fh, _SCALAR_FMT[vtype])
    if vtype == _T_BOOL:
        return bool(_read(fh, "<B"))
    if vtype == _T_STRING:
        return _read_string(fh)
    if vtype == _T_ARRAY:
        etype = _read(fh, "<I")
        n = _read(fh, "<Q")
        return [_read_value(fh, etype) for _ in range(n)]
    raise ValueError(f"unknown GGUF value type {vtype}")


def read_metadata(path: str) -> dict[str, Any]:
    """Parse a GGUF file's metadata KVs (tensor info/data are skipped)."""
    # dtpu: ignore[blocking-call-in-async] -- model-load startup I/O, never on the serving path (allowed-to-block leaf)
    with open(path, "rb") as fh:
        if fh.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path} is not a GGUF file")
        version = _read(fh, "<I")
        if version < 2:
            raise ValueError(f"GGUF v{version} unsupported (need >= 2)")
        _n_tensors = _read(fh, "<Q")
        n_kv = _read(fh, "<Q")
        meta: dict[str, Any] = {"gguf.version": version}
        for _ in range(n_kv):
            key = _read_string(fh)
            vtype = _read(fh, "<I")
            meta[key] = _read_value(fh, vtype)
        return meta


def tokenizer_from_gguf(path: str):
    """Build a dynamo_tpu Tokenizer from a GGUF checkpoint's embedded
    vocabulary (gpt2-style byte-level BPE)."""
    from tokenizers import Tokenizer as HFTokenizer
    from tokenizers import decoders, models, pre_tokenizers

    from dynamo_tpu.llm.tokenizer import Tokenizer

    meta = read_metadata(path)
    model = meta.get("tokenizer.ggml.model")
    tokens = meta.get("tokenizer.ggml.tokens")
    if tokens is None:
        raise ValueError(f"{path} has no tokenizer.ggml.tokens metadata")
    if model != "gpt2":
        raise ValueError(
            f"GGUF tokenizer model {model!r} unsupported (gpt2-style "
            f"byte-level BPE only; sentencepiece GGUFs should ship a "
            f"tokenizer.json instead)")
    merges_raw = meta.get("tokenizer.ggml.merges") or []
    vocab = {tok: i for i, tok in enumerate(tokens)}
    merges = [tuple(m.split(" ", 1)) for m in merges_raw if " " in m]
    hf = HFTokenizer(models.BPE(vocab=vocab, merges=merges))
    hf.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    hf.decoder = decoders.ByteLevel()
    tok = Tokenizer(hf)
    eos = meta.get("tokenizer.ggml.eos_token_id")
    if eos is not None:
        tok.eos_override = [int(eos)]
    return tok
