"""Worker selection: overlap-aware cost with softmax temperature sampling.

Capability parity with reference KvScheduler/DefaultWorkerSelector
(lib/llm/src/kv_router/scheduler.rs:76,361) and KvRouterConfig
(kv_router.rs:88-100): for each candidate worker,

  potential_prefill_blocks = request_blocks - overlap_blocks(worker)
  potential_active_blocks  = worker_active_blocks + request_blocks
  logit = overlap_score_weight * potential_prefill_blocks
          + potential_active_blocks        (lower is better)

With temperature == 0 pick the argmin (ties -> fewest active blocks); with
temperature > 0 sample softmax(-logit / T). busy_threshold rejects when every
worker's KV usage exceeds it (reference WorkerMonitor busy detection + the
router 503 path, tested in test_router_e2e_with_mockers.py:381).
"""

from __future__ import annotations

import dataclasses
import math
import random

from dynamo_tpu.llm.kv_router.indexer import OverlapScores
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.runtime.errors import OverloadedError
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kv_scheduler")


@dataclasses.dataclass
class KvRouterConfig:
    overlap_score_weight: float = 1.0
    temperature: float = 0.0
    busy_threshold: float | None = None  # fraction of KV blocks in use
    block_size: int = 16
    # Federated routing (docs/OBSERVABILITY.md "KV federation"): score
    # each candidate by the UNION of its radix-index overlap (HBM
    # blocks, exact) and its inventory-sketch overlap (host/disk tier
    # blocks the radix dropped on eviction) — so a prompt whose prefix
    # lives anywhere in a worker's tier ladder routes to that worker
    # instead of recomputing elsewhere. False = radix-only (the pre-
    # federation behavior).
    federation: bool = True


class KvScheduler:
    def __init__(self, config: KvRouterConfig,
                 sequences: ActiveSequencesMultiWorker):
        self.config = config
        self.sequences = sequences
        # Latest ForwardPassMetrics per worker.
        self.metrics: dict[int, ForwardPassMetrics] = {}
        # Optional per-worker circuit-breaker board (runtime/overload.py
        # BreakerBoard, shared with the request-plane client): open
        # breakers are excluded from selection before any cost math, so
        # a sick worker stops receiving traffic until its half-open
        # probe succeeds.
        self.health = None

    def update_metrics(self, metrics: ForwardPassMetrics) -> None:
        self.metrics[metrics.worker_id] = metrics

    def remove_worker(self, worker_id: int) -> None:
        self.metrics.pop(worker_id, None)
        self.sequences.remove_worker(worker_id)

    def _predicted_blocks(self, worker_id: int) -> int:
        """Reconciled in-flight block estimate. Worker metrics already include
        requests we dispatched once the engine admits them, so summing metrics
        and our optimistic ledger double-counts; take the max of the two views
        (metrics lag by the publish interval, the ledger lags by completion)."""
        m = self.metrics.get(worker_id)
        observed = m.kv_stats.kv_active_blocks if m else 0
        return max(observed, self.sequences.active_blocks(worker_id))

    def _usage(self, worker_id: int) -> float:
        m = self.metrics.get(worker_id)
        if m is None or m.kv_stats.kv_total_blocks == 0:
            return 0.0
        return min(1.0, self._predicted_blocks(worker_id)
                   / m.kv_stats.kv_total_blocks)

    def select(self, workers: list[int], request_blocks: int,
               overlaps: OverlapScores) -> tuple[int, int]:
        """Pick a worker; returns (worker_id, overlap_blocks). Raises
        OverloadedError (retryable -> 503 + Retry-After at the frontend)
        when every worker is circuit-open or, with busy_threshold set,
        above it."""
        if not workers:
            raise OverloadedError("no candidate workers")
        if self.health is not None:
            admitted = self.health.admitted(workers)
            if not admitted:
                raise OverloadedError(
                    f"all {len(workers)} workers circuit-open "
                    "(consecutive failures); retry shortly")
            workers = admitted
        if self.config.busy_threshold is not None:
            free = [w for w in workers
                    if self._usage(w) < self.config.busy_threshold]
            if not free:
                raise OverloadedError(
                    f"all {len(workers)} workers above busy threshold "
                    f"{self.config.busy_threshold}")
            workers = free
        logits: list[float] = []
        for w in workers:
            overlap = overlaps.get(w, 0)
            potential_prefill = max(0, request_blocks - overlap)
            potential_active = self._predicted_blocks(w) + request_blocks
            # Outstanding prefill work separately from decode residency
            # (reference sequence.rs:225 + prefill_counter.rs): a worker
            # still chewing through big prompts is a bad target even when
            # its resident-block metrics look fine (they lag the publish
            # interval, and under disaggregation prefill cost never shows
            # up as local blocks at all).
            pending_prefill = (self.sequences.prefill_tokens(w)
                               / max(1, self.config.block_size))
            logit = (self.config.overlap_score_weight * potential_prefill
                     + potential_active + pending_prefill)
            logits.append(logit)
        if self.config.temperature <= 0.0:
            best = min(range(len(workers)), key=lambda i: logits[i])
        else:
            t = self.config.temperature
            mx = max(-l / t for l in logits)
            weights = [math.exp(-l / t - mx) for l in logits]
            best = random.choices(range(len(workers)), weights=weights, k=1)[0]
        chosen = workers[best]
        return chosen, overlaps.get(chosen, 0)
