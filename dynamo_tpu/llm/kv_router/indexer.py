"""Radix index of cached KV blocks per worker.

Capability parity with reference RadixTree/KvIndexer (lib/llm/src/kv_router/
indexer.rs:222,641) and ApproxKvIndexer (kv_router/approx.rs): because block
hashes chain their full prefix (tokens.py), the radix structure is implicit in
the hashes — the index maps block_hash -> set(workers that hold it), and
longest-prefix matching walks the request's block hashes in order, narrowing
the worker set. Events arrive from workers (stored/removed/cleared); a worker's
death removes all its blocks (indexer.rs:417 remove_worker).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Iterable

from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent

OverlapScores = dict[int, int]  # worker_id -> number of matched prefix blocks


class PyRadixTree:
    def __init__(self):
        # block_hash -> set of worker ids holding the block.
        self._blocks: dict[int, set[int]] = {}
        # worker_id -> set of block hashes (for remove_worker).
        self._by_worker: dict[int, set[int]] = defaultdict(set)
        self.event_count = 0

    def apply_event(self, event: RouterEvent) -> None:
        """Reference indexer.rs:318 RadixTree::apply_event."""
        self.event_count += 1
        worker = event.worker_id
        ev = event.event
        if ev.kind == "stored":
            for h in ev.block_hashes:
                self._blocks.setdefault(h, set()).add(worker)
                self._by_worker[worker].add(h)
        elif ev.kind == "removed":
            for h in ev.block_hashes:
                workers = self._blocks.get(h)
                if workers is not None:
                    workers.discard(worker)
                    if not workers:
                        del self._blocks[h]
                self._by_worker[worker].discard(h)
        elif ev.kind == "cleared":
            self.remove_worker(worker)

    def remove_worker(self, worker_id: int) -> None:
        """Reference indexer.rs:417."""
        for h in self._by_worker.pop(worker_id, set()):
            workers = self._blocks.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._blocks[h]

    def find_matches(self, block_hashes: Iterable[int]) -> OverlapScores:
        """Longest-prefix overlap per worker (reference indexer.rs:274):
        a worker scores i+1 only if it holds blocks 0..i contiguously."""
        scores: OverlapScores = {}
        active: set[int] | None = None
        for h in block_hashes:
            holders = self._blocks.get(h)
            if not holders:
                break
            active = set(holders) if active is None else active & holders
            if not active:
                break
            for w in active:
                scores[w] = scores.get(w, 0) + 1
        return scores

    def dump_as_events(self) -> list[RouterEvent]:
        """Serialize state for a new router replica (indexer.rs:445
        dump_tree_as_events)."""
        out = []
        for worker, hashes in self._by_worker.items():
            if hashes:
                out.append(RouterEvent(
                    worker_id=worker,
                    event=KvCacheEvent.stored(sorted(hashes))))
        return out

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def workers(self) -> set[int]:
        return {w for w, hs in self._by_worker.items() if hs}


# The C++ core (native/radix_tree.cpp, the role of the reference's Rust
# RadixTree) is preferred when it builds; DTPU_NATIVE=0 or a failed build
# falls back to the pure-Python implementation above. Interfaces are
# identical and parity-tested (tests/test_native_radix.py).
try:
    from dynamo_tpu.native.radix import NativeRadixTree
    from dynamo_tpu.native.radix import available as _native_available
except Exception:  # noqa: BLE001 — any import/build issue -> Python
    _native_available = False

RadixTree = NativeRadixTree if _native_available else PyRadixTree


class KvIndexer:
    """Event-stream-fed indexer bound to a component's kv_events subject
    (reference KvIndexer, indexer.rs:641). The subscription loop lives in the
    router; this object is the synchronous core so it is trivially testable."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self.tree = RadixTree()

    def apply(self, event: RouterEvent) -> None:
        self.tree.apply_event(event)

    def find_matches_for_tokens(self, token_ids: list[int]) -> OverlapScores:
        from dynamo_tpu.llm.tokens import compute_block_hashes

        return self.tree.find_matches(
            compute_block_hashes(token_ids, self.block_size))


class ApproxKvIndexer:
    """TTL-based approximation for engines that emit no KV events (reference
    kv_router/approx.rs:681): on every routing decision the chosen worker is
    assumed to now hold the request's prefix blocks for ``ttl_s``."""

    def __init__(self, block_size: int, ttl_s: float = 120.0):
        self.block_size = block_size
        self.ttl_s = ttl_s
        self.tree = RadixTree()
        self._expiry: list[tuple[float, int, list[int]]] = []
        # Authoritative per-(worker, block) deadline: a re-touch extends it, so
        # an older expiry entry must not remove refreshed blocks.
        self._deadline: dict[tuple[int, int], float] = {}

    def touch(self, worker_id: int, token_ids: list[int]) -> None:
        from dynamo_tpu.llm.tokens import compute_block_hashes

        # Amortized purge: expiry used to run only inside
        # find_matches_for_tokens, so a caller that only touch()es (or a
        # router that stopped matching a quiet worker) let stale entries
        # pin routing decisions past ttl_s. Every mutation now sweeps the
        # expiry heap head first — O(expired) per call, not O(index).
        self.purge()
        hashes = compute_block_hashes(token_ids, self.block_size)
        if not hashes:
            return
        deadline = time.monotonic() + self.ttl_s
        self.tree.apply_event(RouterEvent(
            worker_id=worker_id, event=KvCacheEvent.stored(hashes)))
        for h in hashes:
            self._deadline[(worker_id, h)] = deadline
        self._expiry.append((deadline, worker_id, hashes))

    def purge(self) -> None:
        now = time.monotonic()
        while self._expiry and self._expiry[0][0] <= now:
            _, worker, hashes = self._expiry.pop(0)
            expired = [h for h in hashes
                       if self._deadline.get((worker, h), 0.0) <= now]
            for h in expired:
                self._deadline.pop((worker, h), None)
            if expired:
                self.tree.apply_event(RouterEvent(
                    worker_id=worker, event=KvCacheEvent.removed(expired)))

    def find_matches_for_tokens(self, token_ids: list[int]) -> OverlapScores:
        from dynamo_tpu.llm.tokens import compute_block_hashes

        self.purge()
        return self.tree.find_matches(
            compute_block_hashes(token_ids, self.block_size))
