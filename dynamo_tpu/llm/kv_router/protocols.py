"""KV-router wire protocols: cache events, worker load metrics, and
fleet inventory digests.

Capability parity with reference kv_router/protocols.rs: KvCacheEvent
(stored/removed/cleared, :KvCacheEventData), RouterEvent (worker_id + event),
and ForwardPassMetrics{WorkerStats, KvStats, SpecDecodeStats} (:32-56) that
workers publish each engine iteration. On top of those,
``KvInventoryDigest``: a compact periodic summary of *what KV lives where*
(block counts per tier, capacity headroom, a k-min sketch of the block hash
space) that rides the same event plane — the measured ground the fleet-wide
KV federation round (ROADMAP item 4) builds on, and the source of the
router's `/debug/kv` fleet view (docs/OBSERVABILITY.md "KV & capacity").
"""

from __future__ import annotations

import heapq

from pydantic import BaseModel, Field

#: k-min sketch size: 64 minima of the 64-bit hash space estimate overlap
#: between two workers' inventories to ~±12% — plenty for an operator pane.
SKETCH_K = 64
_HASH_MASK = (1 << 64) - 1


def kmin_sketch(hashes, k: int = SKETCH_K) -> list[int]:
    """The k smallest 64-bit-normalized block hashes: a fixed-size,
    mergeable summary of a hash set (k-minimum-values sketch)."""
    return heapq.nsmallest(k, (h & _HASH_MASK for h in hashes))


def sketch_overlap(a: list[int], b: list[int], k: int = SKETCH_K) -> float:
    """Estimated Jaccard overlap of the two sketched hash sets: the
    fraction of the merged k smallest values present in both sketches."""
    if not a or not b:
        return 0.0
    merged = heapq.nsmallest(min(k, len(a) + len(b)), set(a) | set(b))
    sa, sb = set(a), set(b)
    inter = sum(1 for h in merged if h in sa and h in sb)
    return inter / len(merged)


def sketch_prefix_blocks(sketch: list[int],
                         block_hashes: list[int]) -> int:
    """How many of a request's leading block hashes a sketched inventory
    provably holds — the federated-routing overlap estimate.

    Sound by construction: a k-min sketch stores ACTUAL hash values, so
    membership has no false positives — every counted block really is
    (or very recently was) on that worker. Two regimes:

    - inventory <= k blocks: the sketch IS the complete inventory, so
      this is the exact longest-prefix match (the common case for
      per-model inventories under ~SKETCH_K blocks, and for every
      CI-scale fleet).
    - larger inventories: the sketch is the k smallest hashes — a
      uniform sample of the hash space. A miss is then inconclusive, so
      the walk stops at the first miss and the result is a LOWER bound:
      federated routing degrades gracefully toward the local radix view
      instead of ever overclaiming a prefix a worker doesn't hold.
    """
    if not sketch or not block_hashes:
        return 0
    members = set(sketch)
    n = 0
    for h in block_hashes:
        if (h & _HASH_MASK) in members:
            n += 1
        else:
            break
    return n


class KvStoredBlock(BaseModel):
    block_hash: int
    # tokens are optional diagnostics; the hash is authoritative.
    parent_hash: int | None = None


class KvCacheEvent(BaseModel):
    """stored | removed | cleared."""

    event_id: int = 0
    kind: str  # "stored" | "removed" | "cleared"
    parent_hash: int | None = None  # for stored: parent of the first block
    block_hashes: list[int] = Field(default_factory=list)

    @classmethod
    def stored(cls, block_hashes: list[int], parent_hash: int | None = None,
               event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="stored", parent_hash=parent_hash,
                   block_hashes=block_hashes)

    @classmethod
    def removed(cls, block_hashes: list[int], event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="removed", block_hashes=block_hashes)

    @classmethod
    def cleared(cls, event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="cleared")


class RouterEvent(BaseModel):
    worker_id: int
    event: KvCacheEvent

    def to_wire(self) -> dict:
        return self.model_dump()

    @classmethod
    def from_wire(cls, data: dict) -> "RouterEvent":
        return cls.model_validate(data)


class WorkerStats(BaseModel):
    """Reference kv_router/protocols.rs:40-44."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0
    data_parallel_rank: int | None = None


class KvStats(BaseModel):
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0


class SpecDecodeStats(BaseModel):
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_accepted_tokens: int = 0


class ForwardPassMetrics(BaseModel):
    """Published by workers every engine iteration (reference
    kv_router/publisher.rs:483)."""

    worker_id: int = 0
    worker_stats: WorkerStats = Field(default_factory=WorkerStats)
    kv_stats: KvStats = Field(default_factory=KvStats)
    spec_decode_stats: SpecDecodeStats | None = None

    def to_wire(self) -> dict:
        return self.model_dump(exclude_none=True)

    @classmethod
    def from_wire(cls, data: dict) -> "ForwardPassMetrics":
        return cls.model_validate(data)


class KvInventoryDigest(BaseModel):
    """Periodic per-worker KV inventory summary (worker -> router/planner).

    Deliberately compact: counts + a fixed-size sketch, never the full
    hash list — a 100k-block worker digests to ~1 KB. ``seq`` is a
    per-worker monotonic counter so consumers can drop reordered
    digests; ``ts`` is the publisher's wall clock for staleness."""

    worker_id: int = 0
    seq: int = 0
    ts: float = 0.0
    # Resident registered blocks in HBM (G1) and blocks per offload tier.
    blocks: int = 0
    tier_blocks: dict[str, int] = Field(default_factory=dict)
    # Capacity picture: the router/planner's headroom signal.
    pages_total: int = 0
    pages_free: int = 0
    pages_active: int = 0
    # k-min sketch over every block hash this worker can serve (HBM +
    # host tiers) — overlap between workers is estimable without
    # shipping inventories.
    sketch: list[int] = Field(default_factory=list)

    def to_wire(self) -> dict:
        return self.model_dump()

    @classmethod
    def from_wire(cls, data: dict) -> "KvInventoryDigest":
        return cls.model_validate(data)


# Subjects on the coordinator pub/sub plane (reference kv_router.rs:56-65).
def kv_events_subject(namespace: str, component: str) -> str:
    return f"ns.{namespace}.cp.{component}.kv_events"


def load_metrics_subject(namespace: str, component: str) -> str:
    return f"ns.{namespace}.cp.{component}.load_metrics"


def router_sync_subject(namespace: str, component: str) -> str:
    """Inter-replica router state sync (reference kv_router.rs:64-65)."""
    return f"ns.{namespace}.cp.{component}.router_sync"


def kv_inventory_subject(namespace: str, component: str) -> str:
    """Fleet inventory digests (KvInventoryDigest), alongside kv_events
    and load_metrics on the event plane."""
    return f"ns.{namespace}.cp.{component}.kv_inventory"
