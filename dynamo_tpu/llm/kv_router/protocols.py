"""KV-router wire protocols: cache events and worker load metrics.

Capability parity with reference kv_router/protocols.rs: KvCacheEvent
(stored/removed/cleared, :KvCacheEventData), RouterEvent (worker_id + event),
and ForwardPassMetrics{WorkerStats, KvStats, SpecDecodeStats} (:32-56) that
workers publish each engine iteration.
"""

from __future__ import annotations

from pydantic import BaseModel, Field


class KvStoredBlock(BaseModel):
    block_hash: int
    # tokens are optional diagnostics; the hash is authoritative.
    parent_hash: int | None = None


class KvCacheEvent(BaseModel):
    """stored | removed | cleared."""

    event_id: int = 0
    kind: str  # "stored" | "removed" | "cleared"
    parent_hash: int | None = None  # for stored: parent of the first block
    block_hashes: list[int] = Field(default_factory=list)

    @classmethod
    def stored(cls, block_hashes: list[int], parent_hash: int | None = None,
               event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="stored", parent_hash=parent_hash,
                   block_hashes=block_hashes)

    @classmethod
    def removed(cls, block_hashes: list[int], event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="removed", block_hashes=block_hashes)

    @classmethod
    def cleared(cls, event_id: int = 0) -> "KvCacheEvent":
        return cls(event_id=event_id, kind="cleared")


class RouterEvent(BaseModel):
    worker_id: int
    event: KvCacheEvent

    def to_wire(self) -> dict:
        return self.model_dump()

    @classmethod
    def from_wire(cls, data: dict) -> "RouterEvent":
        return cls.model_validate(data)


class WorkerStats(BaseModel):
    """Reference kv_router/protocols.rs:40-44."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0
    data_parallel_rank: int | None = None


class KvStats(BaseModel):
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0


class SpecDecodeStats(BaseModel):
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_accepted_tokens: int = 0


class ForwardPassMetrics(BaseModel):
    """Published by workers every engine iteration (reference
    kv_router/publisher.rs:483)."""

    worker_id: int = 0
    worker_stats: WorkerStats = Field(default_factory=WorkerStats)
    kv_stats: KvStats = Field(default_factory=KvStats)
    spec_decode_stats: SpecDecodeStats | None = None

    def to_wire(self) -> dict:
        return self.model_dump(exclude_none=True)

    @classmethod
    def from_wire(cls, data: dict) -> "ForwardPassMetrics":
        return cls.model_validate(data)


# Subjects on the coordinator pub/sub plane (reference kv_router.rs:56-65).
def kv_events_subject(namespace: str, component: str) -> str:
    return f"ns.{namespace}.cp.{component}.kv_events"


def load_metrics_subject(namespace: str, component: str) -> str:
    return f"ns.{namespace}.cp.{component}.load_metrics"


def router_sync_subject(namespace: str, component: str) -> str:
    """Inter-replica router state sync (reference kv_router.rs:64-65)."""
    return f"ns.{namespace}.cp.{component}.router_sync"
