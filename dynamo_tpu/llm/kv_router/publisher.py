"""Worker-side publishers: KV cache events and forward-pass load metrics.

Capability parity with reference KvEventPublisher / WorkerMetricsPublisher
(lib/llm/src/kv_router/publisher.rs:101,483): engines call these each
iteration; events/metrics ride the coordinator pub/sub plane on the
component's subjects (reference publishes on NATS, and accepts engine events
over ZMQ — our engine is in-process so no ZMQ hop is needed).
"""

from __future__ import annotations

import asyncio
import itertools
import time

from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvInventoryDigest,
    RouterEvent,
    kv_events_subject,
    kv_inventory_subject,
    load_metrics_subject,
)
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kv_publisher")


class KvEventPublisher:
    def __init__(self, runtime, namespace: str, component: str, worker_id: int):
        self._client = runtime.require_coordinator()
        self.subject = kv_events_subject(namespace, component)
        self.worker_id = worker_id
        self._ids = itertools.count(1)

    async def publish(self, event: KvCacheEvent) -> None:
        event.event_id = next(self._ids)
        router_event = RouterEvent(worker_id=self.worker_id, event=event)
        await self._client.publish(self.subject, router_event.to_wire())

    async def stored(self, block_hashes: list[int],
                     parent_hash: int | None = None) -> None:
        await self.publish(KvCacheEvent.stored(block_hashes, parent_hash))

    async def removed(self, block_hashes: list[int]) -> None:
        await self.publish(KvCacheEvent.removed(block_hashes))

    async def cleared(self) -> None:
        await self.publish(KvCacheEvent.cleared())


class WorkerMetricsPublisher:
    """Publishes ForwardPassMetrics; throttled to at most one message per
    ``min_interval_s`` unless forced (engine iterations can be sub-ms)."""

    def __init__(self, runtime, namespace: str, component: str, worker_id: int,
                 min_interval_s: float = 0.1):
        self._client = runtime.require_coordinator()
        self.subject = load_metrics_subject(namespace, component)
        self.worker_id = worker_id
        self.min_interval_s = min_interval_s
        self._last = 0.0
        self.latest: ForwardPassMetrics | None = None

    async def publish(self, metrics: ForwardPassMetrics,
                      force: bool = False) -> None:
        metrics.worker_id = self.worker_id
        self.latest = metrics
        loop = asyncio.get_running_loop()
        now = loop.time()
        if not force and now - self._last < self.min_interval_s:
            return
        self._last = now
        await self._client.publish(self.subject, metrics.to_wire())


class KvInventoryPublisher:
    """Publishes KvInventoryDigest snapshots on the event plane; the
    digest is a *summary* (counts + sketch) so the default cadence is
    coarser than load metrics — inventories change at block granularity,
    not per token."""

    def __init__(self, runtime, namespace: str, component: str, worker_id: int,
                 min_interval_s: float = 2.0):
        self._client = runtime.require_coordinator()
        self.subject = kv_inventory_subject(namespace, component)
        self.worker_id = worker_id
        self.min_interval_s = min_interval_s
        self._last = 0.0
        self._seq = 0
        self.published = 0
        self._periodic: asyncio.Task | None = None

    def due(self, now: float) -> bool:
        """Cheap engine-loop gate: is the next digest worth building?"""
        return now - self._last >= self.min_interval_s

    async def publish(self, digest: KvInventoryDigest,
                      force: bool = False) -> None:
        loop = asyncio.get_running_loop()
        now = loop.time()
        if not force and now - self._last < self.min_interval_s:
            return
        self._last = now
        self._seq += 1
        digest.worker_id = self.worker_id
        digest.seq = self._seq
        digest.ts = time.time()
        await self._client.publish(self.subject, digest.to_wire())
        self.published += 1

    def start_periodic(self, digest_fn) -> None:
        """Background republish so IDLE workers still advertise inventory:
        the engine loops only publish while processing, but the fleet
        pane must include workers that received no traffic. The throttle
        in publish() dedups against engine-loop publishes."""

        async def loop() -> None:
            while True:
                await asyncio.sleep(self.min_interval_s)
                try:
                    await self.publish(digest_fn())
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — telemetry, keep going
                    # (Includes "dict changed size" races against the
                    # engine thread: the next tick just retries.)
                    log.exception("periodic inventory publish failed")

        if self._periodic is None:
            self._periodic = asyncio.create_task(loop())

    def stop_periodic(self) -> None:
        if self._periodic is not None:
            self._periodic.cancel()
            self._periodic = None
