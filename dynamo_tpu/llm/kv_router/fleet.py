"""Router-side fleet KV state: inventory digests + decision telemetry.

Two small synchronous cores the KV router feeds (subscription loops live
in router.py, same split as KvIndexer):

- ``FleetInventory`` — latest KvInventoryDigest per worker, with
  staleness tracking and pairwise overlap estimation from the hash
  sketches. This is the operator's answer to "what KV lives where":
  blocks per worker and tier, capacity headroom, and how much of the
  hash space workers share (high overlap = the fleet is recomputing
  prefixes a sibling already holds — the federation signal, ROADMAP
  item 4).
- ``DecisionLog`` — per-request routing-decision telemetry: the chosen
  worker's overlap score vs the best available overlap, i.e. "how
  cache-aware was this routing decision actually". A persistent gap
  (regret > 0) means load or health pressure is overriding cache
  affinity — expected under overload, a tuning smell otherwise.
"""

from __future__ import annotations

import collections
import time

from dynamo_tpu.llm.kv_router.protocols import (
    KvInventoryDigest,
    sketch_overlap,
    sketch_prefix_blocks,
)

#: A digest older than this is reported stale (worker dead or its
#: publisher wedged); the prune loop removes the worker soon after.
STALE_S = 30.0


class FleetInventory:
    def __init__(self, stale_s: float = STALE_S):
        self.stale_s = stale_s
        # worker_id -> (received_monotonic, digest)
        self._digests: dict[int, tuple[float, KvInventoryDigest]] = {}
        self.applied = 0
        self.dropped_stale_seq = 0

    def apply(self, digest: KvInventoryDigest) -> bool:
        """Apply one digest; False when a reordered (older-seq) digest
        for the same worker was dropped."""
        prev = self._digests.get(digest.worker_id)
        if prev is not None and digest.seq <= prev[1].seq:
            self.dropped_stale_seq += 1
            return False
        self._digests[digest.worker_id] = (time.monotonic(), digest)
        self.applied += 1
        return True

    def remove_worker(self, worker_id: int) -> None:
        self._digests.pop(worker_id, None)

    def workers(self) -> set[int]:
        return set(self._digests)

    def digest(self, worker_id: int) -> KvInventoryDigest | None:
        entry = self._digests.get(worker_id)
        return entry[1] if entry else None

    def prefix_overlap(self, worker_id: int,
                       block_hashes: list[int]) -> int:
        """Federated overlap estimate for one worker: how many of the
        request's leading blocks this worker's INVENTORY provably holds
        — including host/disk tier blocks the radix index dropped when
        they left HBM (their removed events fired, but the digest sketch
        still covers them). Stale digests score 0: routing on a dead
        worker's inventory would send traffic at a ghost."""
        entry = self._digests.get(worker_id)
        if entry is None:
            return 0
        t, digest = entry
        if time.monotonic() - t > self.stale_s:
            return 0
        return sketch_prefix_blocks(digest.sketch, block_hashes)

    def prefix_overlaps(self, workers, block_hashes: list[int]):
        """Per-worker federated overlap (same shape as the radix
        OverlapScores) for the scheduler's union scoring; zero scores
        are omitted."""
        out: dict[int, int] = {}
        for w in workers:
            n = self.prefix_overlap(w, block_hashes)
            if n > 0:
                out[w] = n
        return out

    def overlap_matrix(self) -> dict[str, float]:
        """Pairwise sketch-estimated inventory overlap, keyed
        "workerhex:workerhex" — small fleets only (O(n^2) pairs)."""
        items = [(w, d.sketch) for w, (_, d) in self._digests.items()
                 if d.sketch]
        out: dict[str, float] = {}
        for i, (wa, sa) in enumerate(items):
            for wb, sb in items[i + 1:]:
                out[f"{wa:x}:{wb:x}"] = round(sketch_overlap(sa, sb), 4)
        return out

    def snapshot(self) -> dict:
        """The /debug/kv fleet block: per-worker inventory + capacity +
        staleness, fleet totals, and the overlap matrix."""
        now = time.monotonic()
        workers: dict[str, dict] = {}
        tot_blocks = tot_pages = tot_free = tot_active = 0
        stale = 0
        for worker_id, (t, d) in sorted(self._digests.items()):
            age = now - t
            is_stale = age > self.stale_s
            stale += is_stale
            workers[f"{worker_id:x}"] = {
                "blocks": d.blocks, "tier_blocks": d.tier_blocks,
                "pages_total": d.pages_total, "pages_free": d.pages_free,
                "pages_active": d.pages_active,
                "headroom": (d.pages_free / d.pages_total
                             if d.pages_total else 0.0),
                "seq": d.seq, "age_s": round(age, 3), "stale": is_stale,
            }
            if not is_stale:
                tot_blocks += d.blocks
                tot_pages += d.pages_total
                tot_free += d.pages_free
                tot_active += d.pages_active
        return {
            "workers": workers,
            "totals": {"workers": len(workers), "stale": stale,
                       "blocks": tot_blocks, "pages_total": tot_pages,
                       "pages_free": tot_free, "pages_active": tot_active},
            "overlap": self.overlap_matrix(),
            "applied": self.applied,
            "dropped_stale_seq": self.dropped_stale_seq,
        }


def _percentile(sorted_vals: list, q: float):
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * q))]


class DecisionLog:
    """Bounded ring of routing decisions: chosen vs best overlap."""

    def __init__(self, capacity: int = 512):
        self._ring: collections.deque[tuple[int, int, int, int]] = \
            collections.deque(maxlen=capacity)
        self.decisions = 0
        self.cache_aware = 0   # chosen overlap == best available overlap
        self.regret_blocks = 0  # cumulative best - chosen

    def note(self, worker_id: int, chosen_overlap: int, best_overlap: int,
             request_blocks: int) -> None:
        self.decisions += 1
        if chosen_overlap >= best_overlap:
            self.cache_aware += 1
        self.regret_blocks += max(0, best_overlap - chosen_overlap)
        self._ring.append((worker_id, chosen_overlap, best_overlap,
                           request_blocks))

    def snapshot(self) -> dict:
        rows = list(self._ring)
        chosen = sorted(c for _, c, _, _ in rows)
        best = sorted(b for _, _, b, _ in rows)
        regret = sorted(max(0, b - c) for _, c, b, _ in rows)
        return {
            "decisions": self.decisions,
            "cache_aware": self.cache_aware,
            "cache_aware_rate": (self.cache_aware / self.decisions
                                 if self.decisions else None),
            "regret_blocks_total": self.regret_blocks,
            "window": len(rows),
            "chosen_overlap_p50": _percentile(chosen, 0.50),
            "chosen_overlap_p99": _percentile(chosen, 0.99),
            "best_overlap_p50": _percentile(best, 0.50),
            "best_overlap_p99": _percentile(best, 0.99),
            "regret_p50": _percentile(regret, 0.50),
            "regret_p99": _percentile(regret, 0.99),
            "recent": [
                {"worker": f"{w:x}", "chosen": c, "best": b, "blocks": n}
                for w, c, b, n in rows[-20:]],
        }
