"""KV-cache-aware routing.

Capability parity with reference lib/llm/src/kv_router (~7.9K LoC): a global
radix index of block hashes per worker fed by worker KV events, a scheduler
costing overlap-weighted prefill work against decode load with softmax
temperature sampling, optimistic in-flight accounting (ActiveSequences), an
approximate TTL indexer variant, worker load metrics, and inter-replica router
sync. The TPU engine and the mocker both emit the same event format.
"""

from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvInventoryDigest,
    KvStats,
    RouterEvent,
    WorkerStats,
)
from dynamo_tpu.llm.kv_router.fleet import DecisionLog, FleetInventory
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.llm.kv_router.router import (
    KvPushRouter,
    make_kv_router_factory,
)
from dynamo_tpu.llm.kv_router.publisher import (
    KvEventPublisher,
    KvInventoryPublisher,
    WorkerMetricsPublisher,
)

__all__ = [
    "DecisionLog",
    "FleetInventory",
    "ForwardPassMetrics",
    "KvCacheEvent",
    "KvEventPublisher",
    "KvIndexer",
    "KvInventoryDigest",
    "KvInventoryPublisher",
    "KvPushRouter",
    "KvRouterConfig",
    "KvScheduler",
    "KvStats",
    "OverlapScores",
    "RadixTree",
    "RouterEvent",
    "WorkerMetricsPublisher",
    "WorkerStats",
    "make_kv_router_factory",
]
