"""The KV-aware router engine.

Capability parity with reference KvRouter/KvPushRouter (lib/llm/src/
kv_router.rs, scheduler.rs, SURVEY.md call stack 3.4): subscribes to the
component's kv_events and load_metrics subjects, maintains the radix index and
per-worker load, and routes each preprocessed request directly to the worker
with the best overlap/load cost. Router replicas stay consistent by
re-publishing their add/free decisions on the router_sync subject
(kv_router.rs:64-65) and by dropping workers when discovery removes them.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import AsyncIterator

from dynamo_tpu.llm.kv_router.fleet import DecisionLog, FleetInventory
from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvInventoryDigest,
    RouterEvent,
    kv_events_subject,
    kv_inventory_subject,
    load_metrics_subject,
    router_sync_subject,
)
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import span

log = get_logger("kv_router")


class KvPushRouter(AsyncEngine):
    def __init__(self, runtime, namespace: str, component: str, client,
                 config: KvRouterConfig):
        self._runtime = runtime
        self.namespace = namespace
        self.component = component
        self.client = client  # EndpointClient
        self.config = config
        self.indexer = KvIndexer(config.block_size)
        self.sequences = ActiveSequencesMultiWorker()
        self.scheduler = KvScheduler(config, self.sequences)
        # Share the request-plane client's circuit-breaker board: the
        # scheduler excludes open workers, the client records outcomes.
        self.scheduler.health = getattr(client, "breakers", None)
        self.replica_id = uuid.uuid4().hex[:8]
        self._tasks: list[asyncio.Task] = []
        self._bg_tasks: set[asyncio.Task] = set()
        self._subs = []
        # Fleet KV observability (docs/OBSERVABILITY.md "KV & capacity"):
        # inventory digests per worker + per-decision chosen-vs-best
        # overlap telemetry, served on /debug/kv in this process.
        self.fleet = FleetInventory()
        self.decisions = DecisionLog()
        # Satellite: KvStats already flow over the load-metrics subject —
        # surface them as labeled gauges on THIS process's /metrics so
        # dashboards can chart fleet KV utilization from the frontend.
        m = runtime.metrics.namespace(namespace).component(component)
        self._g_usage = m.gauge(
            "kv_worker_usage", "Per-worker KV pool usage fraction "
            "(router view of published KvStats)", ["worker"])
        self._g_active_blocks = m.gauge(
            "kv_worker_active_blocks", "Per-worker active KV blocks",
            ["worker"])
        self._g_total_blocks = m.gauge(
            "kv_worker_total_blocks", "Per-worker total KV blocks",
            ["worker"])
        self._g_hit_rate = m.gauge(
            "kv_worker_prefix_hit_rate", "Per-worker prefix-cache hit rate",
            ["worker"])
        self._g_inventory = m.gauge(
            "kv_fleet_inventory_blocks", "Registered KV blocks per worker "
            "from inventory digests", ["worker"])
        self._g_digest_age = m.gauge(
            "kv_fleet_digest_age_seconds", "Age of the newest inventory "
            "digest per worker", ["worker"])
        self._h_overlap = m.histogram(
            "kv_router_overlap_blocks", "Routing-decision overlap scores "
            "in blocks", ["kind"],
            buckets=[0, 1, 2, 4, 8, 16, 32, 64, 128, 256])
        self._c_decisions = m.counter(
            "kv_router_decisions_total", "Routing decisions by cache "
            "awareness", ["outcome"])
        for outcome in ("best", "suboptimal"):
            self._c_decisions.ensure(outcome=outcome)
        # Federation telemetry: which knowledge source produced the
        # winning overlap — "radix" (local index; the pre-federation
        # signal), "inventory" (a digest sketch knew about tier blocks
        # the radix had dropped), or "none" (cold prefix everywhere).
        self._c_federation = m.counter(
            "kv_federation_decisions_total", "Routing decisions by the "
            "source of the chosen worker's overlap score",
            ["source"])
        for source in ("radix", "inventory", "none"):
            self._c_federation.ensure(source=source)

    async def start(self) -> None:
        coord = self._runtime.require_coordinator()
        ev_sub = await coord.subscribe(
            kv_events_subject(self.namespace, self.component))
        load_sub = await coord.subscribe(
            load_metrics_subject(self.namespace, self.component))
        sync_sub = await coord.subscribe(
            router_sync_subject(self.namespace, self.component))
        inv_sub = await coord.subscribe(
            kv_inventory_subject(self.namespace, self.component))
        self._subs = [ev_sub, load_sub, sync_sub, inv_sub]
        self._tasks = [
            asyncio.create_task(self._event_loop(ev_sub)),
            asyncio.create_task(self._load_loop(load_sub)),
            asyncio.create_task(self._sync_loop(sync_sub)),
            asyncio.create_task(self._inventory_loop(inv_sub)),
            asyncio.create_task(self._prune_loop()),
        ]

    async def close(self) -> None:
        for task in self._tasks:
            task.cancel()
        for sub in self._subs:
            await sub.cancel()
        await self.client.close()

    # -- background state maintenance ----------------------------------------
    async def _event_loop(self, sub) -> None:
        async for msg in sub:
            try:
                self.indexer.apply(RouterEvent.from_wire(msg["payload"]))
            except Exception:  # noqa: BLE001
                log.exception("bad kv event")

    async def _load_loop(self, sub) -> None:
        async for msg in sub:
            try:
                metrics = ForwardPassMetrics.from_wire(msg["payload"])
                self.scheduler.update_metrics(metrics)
                worker = f"{metrics.worker_id:x}"
                ks = metrics.kv_stats
                self._g_usage.set(ks.gpu_cache_usage_perc, worker=worker)
                self._g_active_blocks.set(ks.kv_active_blocks, worker=worker)
                self._g_total_blocks.set(ks.kv_total_blocks, worker=worker)
                self._g_hit_rate.set(ks.gpu_prefix_cache_hit_rate,
                                     worker=worker)
            except Exception:  # noqa: BLE001
                log.exception("bad load metrics")

    async def _inventory_loop(self, sub) -> None:
        async for msg in sub:
            try:
                digest = KvInventoryDigest.from_wire(msg["payload"])
                if self.fleet.apply(digest):
                    worker = f"{digest.worker_id:x}"
                    self._g_inventory.set(digest.blocks, worker=worker)
                    self._g_digest_age.set(0.0, worker=worker)
            except Exception:  # noqa: BLE001
                log.exception("bad kv inventory digest")

    async def _sync_loop(self, sub) -> None:
        """Apply other replicas' optimistic add/free events."""
        async for msg in sub:
            payload = msg["payload"]
            if payload.get("replica") == self.replica_id:
                continue
            kind = payload.get("kind")
            if kind == "add":
                self.sequences.add_request(
                    payload["worker_id"], payload["request_id"],
                    payload["blocks"], payload["prefill_tokens"])
            elif kind == "mark":
                self.sequences.mark_prefill_complete(
                    payload["worker_id"], payload["request_id"])
            elif kind == "free":
                self.sequences.free(payload["worker_id"], payload["request_id"])

    async def _prune_loop(self) -> None:
        """Drop state for workers that discovery no longer lists. Requires a
        few consecutive absent ticks before wiping: KV events are incremental,
        so wiping on a transient blip (lease hiccup, watch reconnect) would
        lose a live worker's index forever."""
        absent_ticks: dict[int, int] = {}
        while True:
            await asyncio.sleep(1.0)
            live = set(self.client.instance_ids())
            gone = ((self.indexer.tree.workers()
                     | self.fleet.workers()) - live)
            for worker in gone:
                absent_ticks[worker] = absent_ticks.get(worker, 0) + 1
                if absent_ticks[worker] >= 3:
                    log.info("worker %x gone; dropping its indexed blocks",
                             worker)
                    self.indexer.tree.remove_worker(worker)
                    self.scheduler.remove_worker(worker)
                    self.fleet.remove_worker(worker)
                    hexid = f"{worker:x}"
                    for gauge in (self._g_usage, self._g_active_blocks,
                                  self._g_total_blocks, self._g_hit_rate,
                                  self._g_inventory):
                        gauge.set(0, worker=hexid)
                    absent_ticks.pop(worker, None)
            for worker in list(absent_ticks):
                if worker in live:
                    absent_ticks.pop(worker)
            # Digest staleness: the gauge ages between digests so the
            # dashboard sees a wedged publisher climb, not flatline.
            now = time.monotonic()
            for worker in self.fleet.workers():
                entry = self.fleet._digests.get(worker)
                if entry is not None:
                    self._g_digest_age.set(now - entry[0],
                                           worker=f"{worker:x}")

    def note_worker_leave(self, worker_id: int) -> None:
        """Discovery worker_leave hook (scale-in, crash): drop the
        worker's routing state IMMEDIATELY instead of waiting out the
        prune loop's 3 absent ticks + digest staleness TTL — a retired
        worker's inventory must not keep attracting federated routing,
        and its breaker must not survive into a reincarnation."""
        self.indexer.tree.remove_worker(worker_id)
        self.scheduler.remove_worker(worker_id)
        self.fleet.remove_worker(worker_id)
        breakers = getattr(self.client, "breakers", None)
        if breakers is not None:
            breakers.remove(worker_id)
        hexid = f"{worker_id:x}"
        for gauge in (self._g_usage, self._g_active_blocks,
                      self._g_total_blocks, self._g_hit_rate,
                      self._g_inventory):
            gauge.set(0, worker=hexid)
        log.info("worker %x left; routing state dropped immediately",
                 worker_id)

    def kv_status(self) -> dict:
        """This router's /debug/kv block: index size, fleet inventory
        view, and decision telemetry (runtime/health.py _debug_kv)."""
        return {
            "role": "kv_router",
            "component": self.component,
            "federation": self.config.federation,
            "index": {"blocks": self.indexer.tree.num_blocks,
                      "workers": sorted(f"{w:x}" for w in
                                        self.indexer.tree.workers())},
            "fleet": self.fleet.snapshot(),
            "decisions": self.decisions.snapshot(),
        }

    async def _publish_sync(self, payload: dict) -> None:
        payload["replica"] = self.replica_id
        try:
            await self._runtime.require_coordinator().publish(
                router_sync_subject(self.namespace, self.component), payload)
        except (ConnectionError, RuntimeError):
            pass

    # -- engine interface -----------------------------------------------------
    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        from dynamo_tpu.llm.tokens import compute_block_hashes

        with span("router.decide", mode="kv") as sp:
            # Adapter requests hash under the adapter's chain salt, the
            # SAME chain the worker registers its adapter-conditioned KV
            # under — so per-adapter prefix affinity is exact, while the
            # candidate set / load / KV events all stay keyed on the
            # BASE model (adapters are cheap to replicate: any base
            # worker serves the name, hot-loading on first arrival).
            from dynamo_tpu.llm.tokens import chain_salt
            block_hashes = compute_block_hashes(
                req.token_ids, self.config.block_size,
                salt=chain_salt(getattr(req, "adapter", None)))
            request_blocks = max(1, len(block_hashes))
            radix = self.indexer.tree.find_matches(block_hashes)
            workers = self.client.instance_ids()
            # Federated scoring: union the exact radix view (HBM blocks)
            # with the inventory-sketch view (host/disk tier blocks that
            # left the radix on eviction but are one onboard away on
            # their holder) — per worker, take the larger claim. The
            # sketch estimate never overclaims (sketch_prefix_blocks),
            # so a federated win is a real prefix somewhere in that
            # worker's ladder.
            union = dict(radix)
            for w, est in self.fleet.prefix_overlaps(
                    workers, block_hashes).items():
                if est > union.get(w, 0):
                    union[w] = est
            scoring = union if self.config.federation else radix
            worker_id, _ = self.scheduler.select(
                workers, request_blocks, scoring)
            # The chosen worker's REAL overlap is the union view even
            # when scoring was radix-only (--no-kv-federation): the
            # worker will still onboard from its own tiers on arrival.
            overlap = union.get(worker_id, 0)
            source = ("none" if overlap <= 0
                      else "radix" if radix.get(worker_id, 0) >= overlap
                      else "inventory")
            self._c_federation.inc(source=source)
            # Decision telemetry: chosen-vs-best overlap — how
            # cache-aware this decision actually was. "Best" is over the
            # candidates that COULD have been chosen and over the FLEET
            # view, so both breaker/busy exclusions and federation-off
            # routing count as (visible) regret, not noise — turning
            # federation on makes cache_aware_rate rise on the same
            # workload, which is the ROADMAP item-3 success metric.
            best_overlap = max(union.values(), default=0)
            self.decisions.note(worker_id, overlap, best_overlap,
                                request_blocks)
            self._h_overlap.observe(overlap, kind="chosen")
            self._h_overlap.observe(best_overlap, kind="best")
            self._c_decisions.inc(outcome=("best" if overlap >= best_overlap
                                           else "suboptimal"))
            sp.set(worker_id=f"{worker_id:x}", overlap_blocks=overlap,
                   best_overlap_blocks=best_overlap,
                   request_blocks=request_blocks, overlap_source=source)
            new_blocks = request_blocks - overlap
            request_id = context.id
            prefill_tokens = max(0, len(req.token_ids)
                                 - overlap * self.config.block_size)
            self.sequences.add_request(worker_id, request_id, new_blocks,
                                       prefill_tokens)
            await self._publish_sync({
                "kind": "add", "worker_id": worker_id,
                "request_id": request_id, "blocks": new_blocks,
                "prefill_tokens": prefill_tokens})
        req.estimated_prefix_hit_blocks = overlap
        prefill_done = False
        try:
            stream = await self.client.generate(
                req.to_wire(), context=context, instance_id=worker_id)
            async for item in stream:
                if not prefill_done and isinstance(item, dict) \
                        and item.get("token_ids"):
                    # First token: the worker finished this request's
                    # prefill — drop its outstanding-prefill load.
                    prefill_done = True
                    self.sequences.mark_prefill_complete(worker_id,
                                                         request_id)
                    # Fire-and-forget: replica sync must not add a
                    # coordinator round trip to every request's TTFT. Hold
                    # a reference (the loop keeps tasks only weakly).
                    t = asyncio.ensure_future(self._publish_sync({
                        "kind": "mark", "worker_id": worker_id,
                        "request_id": request_id}))
                    self._bg_tasks.add(t)
                    t.add_done_callback(self._bg_tasks.discard)
                yield item
        finally:
            self.sequences.free(worker_id, request_id)
            await self._publish_sync({
                "kind": "free", "worker_id": worker_id,
                "request_id": request_id})


def make_kv_router_factory(overlap_score_weight: float = 1.0,
                           temperature: float = 0.0,
                           busy_threshold: float | None = None,
                           federation: bool = True):
    """Factory used by ModelWatcher when --router-mode kv is selected."""

    async def factory(runtime, entry, client) -> KvPushRouter:
        config = KvRouterConfig(
            overlap_score_weight=overlap_score_weight,
            temperature=temperature,
            busy_threshold=busy_threshold,
            federation=federation,
            block_size=entry.card.kv_cache_block_size)
        router = KvPushRouter(runtime, entry.namespace, entry.component,
                              client, config)
        await router.start()
        return router

    return factory
