"""The KV-aware router engine.

Capability parity with reference KvRouter/KvPushRouter (lib/llm/src/
kv_router.rs, scheduler.rs, SURVEY.md call stack 3.4): subscribes to the
component's kv_events and load_metrics subjects, maintains the radix index and
per-worker load, and routes each preprocessed request directly to the worker
with the best overlap/load cost. Router replicas stay consistent by
re-publishing their add/free decisions on the router_sync subject
(kv_router.rs:64-65) and by dropping workers when discovery removes them.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import AsyncIterator

from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    RouterEvent,
    kv_events_subject,
    load_metrics_subject,
    router_sync_subject,
)
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import span

log = get_logger("kv_router")


class KvPushRouter(AsyncEngine):
    def __init__(self, runtime, namespace: str, component: str, client,
                 config: KvRouterConfig):
        self._runtime = runtime
        self.namespace = namespace
        self.component = component
        self.client = client  # EndpointClient
        self.config = config
        self.indexer = KvIndexer(config.block_size)
        self.sequences = ActiveSequencesMultiWorker()
        self.scheduler = KvScheduler(config, self.sequences)
        # Share the request-plane client's circuit-breaker board: the
        # scheduler excludes open workers, the client records outcomes.
        self.scheduler.health = getattr(client, "breakers", None)
        self.replica_id = uuid.uuid4().hex[:8]
        self._tasks: list[asyncio.Task] = []
        self._bg_tasks: set[asyncio.Task] = set()
        self._subs = []

    async def start(self) -> None:
        coord = self._runtime.require_coordinator()
        ev_sub = await coord.subscribe(
            kv_events_subject(self.namespace, self.component))
        load_sub = await coord.subscribe(
            load_metrics_subject(self.namespace, self.component))
        sync_sub = await coord.subscribe(
            router_sync_subject(self.namespace, self.component))
        self._subs = [ev_sub, load_sub, sync_sub]
        self._tasks = [
            asyncio.create_task(self._event_loop(ev_sub)),
            asyncio.create_task(self._load_loop(load_sub)),
            asyncio.create_task(self._sync_loop(sync_sub)),
            asyncio.create_task(self._prune_loop()),
        ]

    async def close(self) -> None:
        for task in self._tasks:
            task.cancel()
        for sub in self._subs:
            await sub.cancel()
        await self.client.close()

    # -- background state maintenance ----------------------------------------
    async def _event_loop(self, sub) -> None:
        async for msg in sub:
            try:
                self.indexer.apply(RouterEvent.from_wire(msg["payload"]))
            except Exception:  # noqa: BLE001
                log.exception("bad kv event")

    async def _load_loop(self, sub) -> None:
        async for msg in sub:
            try:
                self.scheduler.update_metrics(
                    ForwardPassMetrics.from_wire(msg["payload"]))
            except Exception:  # noqa: BLE001
                log.exception("bad load metrics")

    async def _sync_loop(self, sub) -> None:
        """Apply other replicas' optimistic add/free events."""
        async for msg in sub:
            payload = msg["payload"]
            if payload.get("replica") == self.replica_id:
                continue
            kind = payload.get("kind")
            if kind == "add":
                self.sequences.add_request(
                    payload["worker_id"], payload["request_id"],
                    payload["blocks"], payload["prefill_tokens"])
            elif kind == "mark":
                self.sequences.mark_prefill_complete(
                    payload["worker_id"], payload["request_id"])
            elif kind == "free":
                self.sequences.free(payload["worker_id"], payload["request_id"])

    async def _prune_loop(self) -> None:
        """Drop state for workers that discovery no longer lists. Requires a
        few consecutive absent ticks before wiping: KV events are incremental,
        so wiping on a transient blip (lease hiccup, watch reconnect) would
        lose a live worker's index forever."""
        absent_ticks: dict[int, int] = {}
        while True:
            await asyncio.sleep(1.0)
            live = set(self.client.instance_ids())
            for worker in self.indexer.tree.workers() - live:
                absent_ticks[worker] = absent_ticks.get(worker, 0) + 1
                if absent_ticks[worker] >= 3:
                    log.info("worker %x gone; dropping its indexed blocks",
                             worker)
                    self.indexer.tree.remove_worker(worker)
                    self.scheduler.remove_worker(worker)
                    absent_ticks.pop(worker, None)
            for worker in list(absent_ticks):
                if worker in live:
                    absent_ticks.pop(worker)

    async def _publish_sync(self, payload: dict) -> None:
        payload["replica"] = self.replica_id
        try:
            await self._runtime.require_coordinator().publish(
                router_sync_subject(self.namespace, self.component), payload)
        except (ConnectionError, RuntimeError):
            pass

    # -- engine interface -----------------------------------------------------
    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        from dynamo_tpu.llm.tokens import compute_block_hashes

        with span("router.decide", mode="kv") as sp:
            block_hashes = compute_block_hashes(req.token_ids,
                                                self.config.block_size)
            request_blocks = max(1, len(block_hashes))
            overlaps = self.indexer.tree.find_matches(block_hashes)
            workers = self.client.instance_ids()
            worker_id, overlap = self.scheduler.select(
                workers, request_blocks, overlaps)
            sp.set(worker_id=f"{worker_id:x}", overlap_blocks=overlap,
                   request_blocks=request_blocks)
            new_blocks = request_blocks - overlap
            request_id = context.id
            prefill_tokens = max(0, len(req.token_ids)
                                 - overlap * self.config.block_size)
            self.sequences.add_request(worker_id, request_id, new_blocks,
                                       prefill_tokens)
            await self._publish_sync({
                "kind": "add", "worker_id": worker_id,
                "request_id": request_id, "blocks": new_blocks,
                "prefill_tokens": prefill_tokens})
        req.estimated_prefix_hit_blocks = overlap
        prefill_done = False
        try:
            stream = await self.client.generate(
                req.to_wire(), context=context, instance_id=worker_id)
            async for item in stream:
                if not prefill_done and isinstance(item, dict) \
                        and item.get("token_ids"):
                    # First token: the worker finished this request's
                    # prefill — drop its outstanding-prefill load.
                    prefill_done = True
                    self.sequences.mark_prefill_complete(worker_id,
                                                         request_id)
                    # Fire-and-forget: replica sync must not add a
                    # coordinator round trip to every request's TTFT. Hold
                    # a reference (the loop keeps tasks only weakly).
                    t = asyncio.ensure_future(self._publish_sync({
                        "kind": "mark", "worker_id": worker_id,
                        "request_id": request_id}))
                    self._bg_tasks.add(t)
                    t.add_done_callback(self._bg_tasks.discard)
                yield item
        finally:
            self.sequences.free(worker_id, request_id)
            await self._publish_sync({
                "kind": "free", "worker_id": worker_id,
                "request_id": request_id})


def make_kv_router_factory(overlap_score_weight: float = 1.0,
                           temperature: float = 0.0,
                           busy_threshold: float | None = None):
    """Factory used by ModelWatcher when --router-mode kv is selected."""

    async def factory(runtime, entry, client) -> KvPushRouter:
        config = KvRouterConfig(
            overlap_score_weight=overlap_score_weight,
            temperature=temperature,
            busy_threshold=busy_threshold,
            block_size=entry.card.kv_cache_block_size)
        router = KvPushRouter(runtime, entry.namespace, entry.component,
                              client, config)
        await router.start()
        return router

    return factory
