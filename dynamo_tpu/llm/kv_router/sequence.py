"""Optimistic in-flight accounting per worker.

Capability parity with reference ActiveSequences/ActiveSequencesMultiWorker
(lib/llm/src/kv_router/sequence.rs:48,225): between worker metric updates the
router tracks, per worker, the blocks and decode sequences it has dispatched
itself, so consecutive routing decisions see each other's load immediately.
Replica routers exchange the same add/free/mark events (router_sync subject).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class _WorkerLoad:
    active_blocks: int = 0
    active_seqs: int = 0
    prefill_tokens: int = 0
    requests: dict[str, tuple[int, int]] = field(default_factory=dict)
    # request_id -> (blocks, prefill_tokens)


class ActiveSequencesMultiWorker:
    def __init__(self):
        self._workers: dict[int, _WorkerLoad] = {}

    def ensure_worker(self, worker_id: int) -> _WorkerLoad:
        return self._workers.setdefault(worker_id, _WorkerLoad())

    def remove_worker(self, worker_id: int) -> None:
        self._workers.pop(worker_id, None)

    def add_request(self, worker_id: int, request_id: str, new_blocks: int,
                    prefill_tokens: int) -> None:
        load = self.ensure_worker(worker_id)
        load.requests[request_id] = (new_blocks, prefill_tokens)
        load.active_blocks += new_blocks
        load.active_seqs += 1
        load.prefill_tokens += prefill_tokens

    def mark_prefill_complete(self, worker_id: int, request_id: str) -> None:
        load = self._workers.get(worker_id)
        if load is None:
            return
        entry = load.requests.get(request_id)
        if entry is None:
            return
        blocks, prefill = entry
        load.requests[request_id] = (blocks, 0)
        load.prefill_tokens -= prefill

    def free(self, worker_id: int, request_id: str) -> None:
        load = self._workers.get(worker_id)
        if load is None:
            return
        entry = load.requests.pop(request_id, None)
        if entry is None:
            return
        blocks, prefill = entry
        load.active_blocks -= blocks
        load.active_seqs -= 1
        load.prefill_tokens -= prefill

    def active_blocks(self, worker_id: int) -> int:
        load = self._workers.get(worker_id)
        return load.active_blocks if load else 0

    def active_seqs(self, worker_id: int) -> int:
        load = self._workers.get(worker_id)
        return load.active_seqs if load else 0

    def prefill_tokens(self, worker_id: int) -> int:
        load = self._workers.get(worker_id)
        return load.prefill_tokens if load else 0
