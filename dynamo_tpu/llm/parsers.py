"""Tool-call and reasoning parsers for the backward (detokenized) edge.

Capability parity with the reference parser crate
(lib/parsers/src/tool_calling/parsers.rs, reasoning/deepseek_r1_parser.rs):
config-driven JSON tool-call extraction for the common model formats and
<think>-style reasoning splitting, in batch AND streaming forms. The
streaming parser "jails" output once a start marker (or its prefix at the
buffer tail) appears, so tool-call JSON never leaks into content deltas.

Formats (reference parsers.rs:44-126):
- hermes:        <tool_call>{...}</tool_call>          (one call per block)
- nemotron_deci: <TOOLCALL>[{...}, ...]</TOOLCALL>
- llama3_json:   <|python_tag|>{...} or a bare leading JSON object
- mistral:       [TOOL_CALLS][{...}, ...]
- phi4:          functools[{...}, ...]
- default:       <TOOLCALL>/<|python_tag|> or bare JSON

A payload may be one object, a JSON array of objects, or ';'-separated
objects; the function name is under "name", arguments under "arguments"
or "parameters" (json_parser.rs:114-126).
"""

from __future__ import annotations

import dataclasses
import json
import uuid


@dataclasses.dataclass
class ToolCall:
    name: str
    arguments: str  # JSON-encoded string (OpenAI wire format)
    id: str = dataclasses.field(
        default_factory=lambda: f"call-{uuid.uuid4().hex[:24]}")

    def to_openai(self, index: int = 0) -> dict:
        return {"id": self.id, "type": "function", "index": index,
                "function": {"name": self.name, "arguments": self.arguments}}


@dataclasses.dataclass
class ToolFormat:
    start_tokens: list[str]
    end_tokens: list[str]          # "" = runs to end of text
    bare_json_ok: bool = False     # a leading '{'/'[' starts a call


TOOL_FORMATS: dict[str, ToolFormat] = {
    "hermes": ToolFormat(["<tool_call>"], ["</tool_call>"]),
    "nemotron_deci": ToolFormat(["<TOOLCALL>"], ["</TOOLCALL>"]),
    "llama3_json": ToolFormat(["<|python_tag|>"], [""], bare_json_ok=True),
    "mistral": ToolFormat(["[TOOL_CALLS]"], [""]),
    "phi4": ToolFormat(["functools"], [""]),
    "default": ToolFormat(["<TOOLCALL>", "<|python_tag|>"], ["</TOOLCALL>", ""],
                          bare_json_ok=True),
}

NAME_KEYS = ("name",)
ARG_KEYS = ("arguments", "parameters")


def _calls_from_payload(payload: str) -> list[ToolCall]:
    """Parse one payload region: a JSON object, an array of objects, or
    ';'-separated objects."""
    payload = payload.strip()
    if not payload:
        return []
    candidates: list = []
    try:
        doc = json.loads(payload)
        candidates = doc if isinstance(doc, list) else [doc]
    except json.JSONDecodeError:
        for part in payload.split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                doc = json.loads(part)
            except json.JSONDecodeError:
                continue
            candidates.extend(doc if isinstance(doc, list) else [doc])
    out = []
    for item in candidates:
        if not isinstance(item, dict):
            continue
        name = next((item[k] for k in NAME_KEYS if k in item), None)
        args = next((item[k] for k in ARG_KEYS if k in item), None)
        if name is None:
            continue
        if not isinstance(args, str):
            args = json.dumps(args if args is not None else {})
        out.append(ToolCall(name=str(name), arguments=args))
    return out


def parse_tool_calls(text: str, parser: str) -> tuple[str, list[ToolCall]]:
    """Batch parse: returns (normal_text, calls). Unknown/None parser names
    pass the text through untouched."""
    fmt = TOOL_FORMATS.get(parser or "")
    if fmt is None:
        return text, []
    calls: list[ToolCall] = []
    normal: list[str] = []
    rest = text
    while rest:
        # Earliest start marker.
        hit = None
        for si, tok in enumerate(fmt.start_tokens):
            pos = rest.find(tok)
            if pos >= 0 and (hit is None or pos < hit[0]):
                hit = (pos, si, tok)
        if hit is None:
            if fmt.bare_json_ok and rest.lstrip()[:1] in ("{", "["):
                got = _calls_from_payload(rest)
                if got:
                    calls.extend(got)
                    rest = ""
                    continue
            normal.append(rest)
            break
        pos, si, tok = hit
        normal.append(rest[:pos])
        after = rest[pos + len(tok):]
        end_tok = (fmt.end_tokens[si]
                   if si < len(fmt.end_tokens) else "").strip()
        if end_tok:
            end = after.find(end_tok)
            if end < 0:
                payload, rest = after, ""
            else:
                payload, rest = after[:end], after[end + len(end_tok):]
        else:
            payload, rest = after, ""
        calls.extend(_calls_from_payload(payload))
    return "".join(normal).strip("\n"), calls


class StreamingToolCallParser:
    """Incremental tool-call extraction: feed text deltas; content before
    any marker streams through, everything after is jailed until finish.
    A marker PREFIX at the buffer tail is held back too, so markers split
    across deltas never leak."""

    def __init__(self, parser: str):
        self.fmt = TOOL_FORMATS.get(parser or "")
        self.buf = ""
        self.jailed = False
        self._emitted = False  # any content already streamed out

    def _tail_holdback(self) -> int:
        """Length of the longest start-token prefix the buffer ends with."""
        assert self.fmt is not None
        best = 0
        for tok in self.fmt.start_tokens:
            for k in range(min(len(tok), len(self.buf)), 0, -1):
                if self.buf.endswith(tok[:k]):
                    best = max(best, k)
                    break
        return best

    def feed(self, delta: str) -> str:
        """Returns the content safe to emit now ('' while jailed)."""
        if self.fmt is None:
            return delta
        self.buf += delta
        if self.jailed:
            return ""
        for tok in self.fmt.start_tokens:
            if tok in self.buf:
                pos = self.buf.find(tok)
                visible = self.buf[:pos]
                self.buf = self.buf[pos:]
                self.jailed = True
                if visible:
                    self._emitted = True
                return visible
        # Bare-JSON only counts at RESPONSE start (matching the batch
        # parser's leading-JSON rule) — mid-answer JSON is just content.
        if (self.fmt.bare_json_ok and not self._emitted
                and self.buf.lstrip()[:1] in ("{", "[")):
            self.jailed = True
            return ""
        hold = self._tail_holdback()
        visible = self.buf[:len(self.buf) - hold] if hold else self.buf
        self.buf = self.buf[len(visible):]
        if visible.strip():
            self._emitted = True
        return visible

    def finish(self) -> tuple[str, list[ToolCall]]:
        """Flush: parse anything jailed; returns (trailing_text, calls) —
        non-call text around the parsed blocks is preserved."""
        if self.fmt is None or not self.buf:
            return "", []
        text, calls = parse_tool_calls(self.buf, _fmt_name(self.fmt))
        self.buf = ""
        return text, calls


def _fmt_name(fmt: ToolFormat) -> str:
    for name, f in TOOL_FORMATS.items():
        if f is fmt:
            return name
    return "default"


# ---------------------------------------------------------------------------
# Reasoning (think-tag) parsing — reference reasoning/deepseek_r1_parser.rs
# ---------------------------------------------------------------------------

REASONING_FORMATS: dict[str, tuple[str, str, bool]] = {
    # name: (open, close, starts_in_reasoning) — DeepSeek-R1 templates
    # often omit the opening tag (generation starts inside the think
    # block), hence the basic/forced split.
    "deepseek_r1": ("<think>", "</think>", True),
    "basic": ("<think>", "</think>", False),
}


def parse_reasoning(text: str, parser: str) -> tuple[str, str]:
    """Batch split -> (content, reasoning_content)."""
    fmt = REASONING_FORMATS.get(parser or "")
    if fmt is None:
        return text, ""
    open_t, close_t, starts_in = fmt
    reasoning: list[str] = []
    content: list[str] = []
    rest = text
    in_think = starts_in and not rest.lstrip().startswith(open_t)
    while rest:
        if in_think:
            end = rest.find(close_t)
            if end < 0:
                reasoning.append(rest)
                break
            reasoning.append(rest[:end])
            rest = rest[end + len(close_t):]
            in_think = False
        else:
            start = rest.find(open_t)
            if start < 0:
                content.append(rest)
                break
            content.append(rest[:start])
            rest = rest[start + len(open_t):]
            in_think = True
    return "".join(content).strip("\n"), "".join(reasoning).strip("\n")


class StreamingReasoningParser:
    """Incremental think-tag splitting: feed(delta) ->
    (content_delta, reasoning_delta), with tag-prefix holdback at the
    buffer tail."""

    def __init__(self, parser: str):
        self.fmt = REASONING_FORMATS.get(parser or "")
        self.buf = ""
        self.started = False
        self.in_think = False

    def feed(self, delta: str) -> tuple[str, str]:
        if self.fmt is None:
            return delta, ""
        open_t, close_t, starts_in = self.fmt
        self.buf += delta
        if not self.started:
            s = self.buf.lstrip()
            if not s:
                return "", ""
            if starts_in and open_t.startswith(s):
                # Could still become the opening tag: hold until decidable.
                return "", ""
            self.started = True
            if starts_in and not s.startswith(open_t):
                self.in_think = True
        content, reasoning = [], []
        while True:
            tok = close_t if self.in_think else open_t
            pos = self.buf.find(tok)
            if pos >= 0:
                (reasoning if self.in_think else content).append(
                    self.buf[:pos])
                self.buf = self.buf[pos + len(tok):]
                self.in_think = not self.in_think
                continue
            # Hold back a possible split tag at the tail.
            hold = 0
            for k in range(min(len(tok), len(self.buf)), 0, -1):
                if self.buf.endswith(tok[:k]):
                    hold = k
                    break
            emit = self.buf[:len(self.buf) - hold]
            self.buf = self.buf[len(self.buf) - hold:]
            (reasoning if self.in_think else content).append(emit)
            break
        return "".join(content), "".join(reasoning)

    def finish(self) -> tuple[str, str]:
        out = self.feed("")
        tail_c, tail_r = ("", self.buf) if self.in_think else (self.buf, "")
        self.buf = ""
        return out[0] + tail_c, out[1] + tail_r
