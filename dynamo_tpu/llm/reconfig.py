"""Live xPyD role reconfiguration: the worker-side transition protocol.

The reference's headline capability #1 is disaggregated prefill/decode
that is *runtime-reconfigurable* (PAPER.md §0) — in the spirit of
DistServe's goodput-optimal prefill/decode partitioning and Splitwise's
phase-split pool resizing. This module lets a running worker flip
between ``prefill``, ``decode``, and ``agg`` without dropping a single
in-flight request and without reloading weights:

- A ``SetRole`` control verb moves the worker through an explicit state
  machine ``serving -> draining -> flipping -> serving``. Draining
  reuses the retire/migration machinery: the old serving profile's
  endpoint servers deregister from discovery (routers stop selecting
  the worker immediately), in-flight streams finish within the drain
  window or are killed with a TYPED ``incomplete:role_flip`` frame that
  the client's Migration operator turns into a re-issue on another
  worker (llm/migration.py; the accounting ledger records
  ``migration_reason="role_flip"``).
- The flip tears down the old profile's watchers/clients/queue workers
  and builds the new role's profile — new endpoint registrations via
  discovery, rewired prefill-queue and disagg watchers — around the
  SAME engine object (no weight reload).
- Every directive is **epoch-fenced**: a worker applies a directive iff
  its epoch is strictly greater than the last applied epoch, so
  duplicated or reordered SetRole frames are idempotent/rejected typed
  (RoleTransitionError), and a replayed directive (coordinator watch
  reconnect re-delivers its snapshot) cannot re-run a finished flip.
  Planner-issued directives additionally ride the PLANNER's lease
  (planner/reconfig.py): a planner that dies after issuing loses the
  directive key with its lease, so a stale flip can't apply later.

Coordinator schema::

    role/<namespace>/<worker_hex>        -> RoleDirective (issuer's lease)
    rolestatus/<namespace>/<worker_hex>  -> worker status (worker's lease)

The status key rides the worker's primary lease: a worker that crashes
mid-drain simply vanishes from the fleet view and its streams migrate —
the fleet converges without operator action. Crash-safety of the
coordinator itself comes from the client's reconnect replay
(runtime/coordinator_client.py): the directive watch is re-established
and the status re-put via the lease-recreated callback.

Observability: ``role_flips_total{from,to,outcome}``, the
``worker_role`` gauge, and a ``role.flip`` span with ``role.drain`` /
``role.reregister`` phase children (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.errors import RoleTransitionError
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, policies
from dynamo_tpu.runtime.tracing import span

log = get_logger("reconfig")

#: The roles a worker can serve. ``agg`` = fully local prefill+decode.
ROLES = ("prefill", "decode", "agg")

#: Why a stream died during a drain — the typed migration reason.
DRAIN_REASON = "role_flip"

#: The scale-in flavor: a retire drain kills leftovers with
#: ``incomplete:scale_in`` frames so the ledger can attribute the
#: migration cost to autoscaling, not to role flips.
SCALE_IN_REASON = "scale_in"

ROLE_ROOT = "role/"
ROLE_STATUS_ROOT = "rolestatus/"


def role_key(namespace: str, worker_id: int) -> str:
    """The directive key the worker watches for SetRole verbs."""
    return f"{ROLE_ROOT}{namespace}/{worker_id:x}"


def role_status_key(namespace: str, worker_id: int) -> str:
    """The status key the worker publishes its state machine on."""
    return f"{ROLE_STATUS_ROOT}{namespace}/{worker_id:x}"


class RoleState:
    """Worker role state machine states (docs/RESILIENCE.md)."""

    SERVING = "serving"
    DRAINING = "draining"
    FLIPPING = "flipping"
    # Terminal: a scale-in retire drained this worker out of the fleet
    # (llm/standby.py scale directives). The status key vanishes with
    # the worker's lease moments later; "retired" is the short-lived
    # honest answer in between.
    RETIRED = "retired"


#: role_flips_total outcome vocabulary. ``ok``/``failed`` terminate a
#: real transition; the rest are fencing decisions on the verb itself.
FLIP_OUTCOMES = ("ok", "failed", "noop", "duplicate", "rejected_stale",
                 "rejected_busy")


class ServingProfile:
    """Everything one role serves: endpoint servers plus the closers for
    role-specific machinery (prefill queue workers, disagg clients and
    config watchers, queue dispatchers). Built per role by the worker
    main's profile factory; the engine itself lives OUTSIDE the profile
    and survives flips."""

    def __init__(self, role: str):
        self.role = role
        self.servers: list = []          # EndpointServer instances
        self._closers: list[tuple[str, Callable[[], Awaitable]]] = []
        self.pausables: list = []        # objects with .pause() (queue pulls)

    def add_server(self, server) -> "ServingProfile":
        self.servers.append(server)
        return self

    def add_closer(self, name: str, fn: Callable[[], Awaitable]
                   ) -> "ServingProfile":
        """Async teardown for role-specific machinery, run (reverse
        order) during the flip phase — after the drain."""
        self._closers.append((name, fn))
        return self

    def add_pausable(self, obj) -> "ServingProfile":
        """Something with a ``pause()`` method that must stop pulling
        NEW work the moment the drain starts (QueuePrefillWorker: a
        draining prefill worker must leave queue items to its peers)."""
        self.pausables.append(obj)
        return self

    @property
    def inflight(self) -> int:
        return sum(len(getattr(s, "_inflight", ())) for s in self.servers)

    async def drain(self, drain_s: float, reason: str = DRAIN_REASON) -> None:
        """Deregister every server and drain in-flight streams up to the
        deadline; leftovers are killed with typed incomplete frames."""
        for obj in self.pausables:
            try:
                obj.pause()
            except Exception:  # noqa: BLE001 — pausing is best-effort
                log.exception("pause during drain failed")
        for server in self.servers:
            await server.shutdown(drain_s=drain_s, reason=reason)

    async def close(self) -> None:
        """Tear down role-specific machinery (watchers, clients, queue
        workers). Servers are already down after drain()."""
        for name, fn in reversed(self._closers):
            try:
                await fn()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — teardown must not wedge a flip
                log.exception("profile closer %s failed", name)
        self._closers.clear()
        self.servers.clear()
        self.pausables.clear()


class RoleManager:
    """Worker-side owner of the role state machine.

    ``build_profile(role) -> ServingProfile`` is the only hook a worker
    main provides: it registers the role's endpoints around the shared
    engine. The manager serializes SetRole verbs (from the coordinator
    directive watch AND the status server's HTTP control path) through
    one lock, fences them by epoch, and publishes its state on the
    coordinator for the planner/doctor fleet view.
    """

    def __init__(self, runtime, build_profile:
                 Callable[[str], Awaitable[ServingProfile]],
                 role: str = "agg", namespace: str | None = None,
                 drain_s: float | None = None,
                 status_extra: dict | None = None):
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r} (want one of {ROLES})")
        self._runtime = runtime
        self._build = build_profile
        self.role = role
        self.namespace = namespace or runtime.config.namespace
        self.state = RoleState.SERVING
        self.applied_epoch = 0
        self.target_role: str | None = None
        self._inflight_epoch: int | None = None
        self.last_outcome: dict | None = None
        self.profile: ServingProfile | None = None
        self.drain_s = (drain_s if drain_s is not None
                        else runtime.config.retire_drain_s)
        self._extra = dict(status_extra or {})
        # Scale-in hook: called once after a retire() drain completes
        # (worker mains wire runtime.shutdown so the process exits and
        # its lease — and status key — die with it).
        self._on_retired: Callable | None = None
        self._lock = asyncio.Lock()
        self._watch = None
        self._watch_task: asyncio.Task | None = None
        self.flips = 0
        metrics = getattr(runtime, "metrics", None)
        self._m_flips = self._m_role = None
        if metrics is not None:
            self._m_flips = metrics.counter(
                "role_flips_total",
                "Worker role transitions by source/target/outcome",
                ["from", "to", "outcome"])
            self._m_role = metrics.gauge(
                "worker_role", "Current serving role (1 on exactly one "
                "role label per worker)", ["role"])

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        """Build the initial profile, publish status, watch directives."""
        self.profile = await self._build(self.role)
        self._set_role_gauge()
        if self._runtime.has_discovery:
            client = self._runtime.require_coordinator()
            await self._write_status()
            client.on_lease_recreated(self._on_lease_recreated)
            self._watch = await client.watch_prefix(
                role_key(self.namespace, self._runtime.instance_id))
            for item in self._watch.snapshot:
                # A directive issued while we were (re)starting: apply it
                # now — epoch fencing makes replays harmless.
                await self._apply_directive(item["v"])
            self._watch_task = asyncio.create_task(self._watch_loop())

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch is not None:
            await self._watch.cancel()
        if self.profile is not None:
            for server in self.profile.servers:
                await server.shutdown()
            await self.profile.close()
            self.profile = None

    async def _on_lease_recreated(self, _new_lease_id: int) -> None:
        """Status rides our primary lease: re-put after a regrant so the
        fleet view doesn't silently lose this worker."""
        await self._write_status()

    # -- the SetRole verb -----------------------------------------------------
    async def set_role(self, role: str, epoch: int,
                       issued_by: str = "operator",
                       drain_s: float | None = None,
                       cause: str | None = None) -> dict:
        """Apply one SetRole directive. ``cause`` is the issuer's
        journal ref (a planner_decision event rides the directive) so
        the worker's flip events chain back to the decision that issued
        them. Returns the outcome record; raises RoleTransitionError
        (typed, wire-prefixed) on fencing rejections — unknown role,
        stale/duplicate epoch, or a conflicting flip already in
        flight."""
        if role not in ROLES:
            raise RoleTransitionError(
                f"unknown role {role!r} (want one of {ROLES})")
        if self.state == RoleState.RETIRED:
            raise RoleTransitionError(
                "worker is retired (scale-in drained it); no further "
                "role transitions apply")
        epoch = int(epoch)
        if self._lock.locked():
            # Fast-path fencing against the in-flight flip WITHOUT
            # queueing behind it: a duplicate of the running directive is
            # acknowledged, anything else is rejected busy.
            if (self.target_role == role
                    and self._inflight_epoch == epoch):
                return {"from": self.role, "to": role, "epoch": epoch,
                        "outcome": "duplicate", "state": self.state}
            self._note_fence(self.role, role, epoch, "rejected_busy",
                             cause=cause)
            raise RoleTransitionError(
                f"flip to {self.target_role!r} (epoch "
                f"{self._inflight_epoch}) in flight; retry after it "
                "converges")
        async with self._lock:
            if self.state == RoleState.RETIRED:
                # A retire won the race for the lock: this worker is out
                # of the fleet, the flip must target someone else.
                self._note_fence(self.role, role, epoch, "rejected_stale",
                                 cause=cause)
                raise RoleTransitionError(
                    "worker retired while the flip waited; no further "
                    "role transitions apply")
            if epoch <= self.applied_epoch:
                if role == self.role and epoch == self.applied_epoch:
                    # Exact duplicate of the applied directive: idempotent.
                    return {"from": self.role, "to": role, "epoch": epoch,
                            "outcome": "duplicate", "state": self.state}
                self._note_fence(self.role, role, epoch, "rejected_stale",
                                 cause=cause)
                raise RoleTransitionError(
                    f"stale epoch {epoch} (applied epoch "
                    f"{self.applied_epoch}, role {self.role!r})")
            if role == self.role:
                # Fence forward without a transition.
                self.applied_epoch = epoch
                self.last_outcome = self._outcome(role, role, epoch, "noop")
                journal.emit(EventKind.ROLE_FLIP_DONE, cause=cause,
                             **{"from": role, "to": role, "epoch": epoch,
                                "outcome": "noop"})
                await self._write_status()
                return self.last_outcome
            return await self._flip(role, epoch, issued_by, drain_s, cause)

    async def _flip(self, role: str, epoch: int, issued_by: str,
                    drain_s: float | None,
                    cause: str | None = None) -> dict:
        old = self.role
        self.target_role = role
        self._inflight_epoch = epoch
        outcome, error = "ok", None
        budget = self.drain_s if drain_s is None else drain_s
        log.info("role flip %s -> %s (epoch %d, by %s): draining up to "
                 "%.1fs", old, role, epoch, issued_by, budget)
        # Every state-machine edge lands on the decision plane, each
        # edge caused by the previous one (and the first by the
        # issuer's decision event when the directive carried its ref).
        requested_ref = journal.emit(
            EventKind.ROLE_FLIP_REQUESTED, cause=cause,
            **{"from": old, "to": role, "epoch": epoch,
               "issued_by": issued_by})
        with span("role.flip", to=role, epoch=epoch, issued_by=issued_by,
                  **{"from": old}) as sp:
            try:
                self.state = RoleState.DRAINING
                drain_ref = journal.emit(
                    EventKind.ROLE_FLIP_DRAINING, cause=requested_ref,
                    **{"from": old, "to": role, "epoch": epoch,
                       "inflight": self.profile.inflight,
                       "drain_s": budget})
                requested_ref = drain_ref
                await self._write_status()
                with span("role.drain", inflight=self.profile.inflight):
                    await self.profile.drain(budget, reason=DRAIN_REASON)
                self.state = RoleState.FLIPPING
                await self._write_status()
                with span("role.reregister"):
                    await self.profile.close()
                    self.profile = None
                    self.profile = await self._build_with_retry(role)
                self.role = role
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — typed outcome, no wedge
                outcome, error = "failed", f"{type(exc).__name__}: {exc}"
                log.exception("role flip %s -> %s failed", old, role)
                if self.profile is None:
                    # Roll back to serving the OLD role rather than
                    # leaving the worker serving nothing.
                    try:
                        self.profile = await self._build_with_retry(old)
                    except Exception:  # noqa: BLE001 — report, stay degraded
                        outcome = "failed_unserved"
                        log.exception("rollback to role %s failed: worker "
                                      "is serving NOTHING", old)
            finally:
                self.applied_epoch = epoch
                self.state = RoleState.SERVING
                self.target_role = None
                self._inflight_epoch = None
                self.flips += 1
                self.last_outcome = self._outcome(old, role, epoch, outcome,
                                                  error)
                journal.emit(EventKind.ROLE_FLIP_DONE, cause=requested_ref,
                             **{"from": old, "to": role, "epoch": epoch,
                                "outcome": outcome,
                                **({"error": error} if error else {})})
                sp.set(outcome=outcome)
                if self._m_flips is not None:
                    self._m_flips.inc(**{"from": old, "to": role,
                                         "outcome": outcome})
                self._set_role_gauge()
                await self._write_status()
        log.info("role flip %s -> %s (epoch %d): %s", old, role, epoch,
                 outcome)
        return self.last_outcome

    # -- the Retire verb (scale-in; planner/capacity.py) ----------------------
    async def retire(self, epoch: int, issued_by: str = "planner",
                     drain_s: float | None = None,
                     cause: str | None = None) -> dict:
        """Drain this worker OUT of the fleet (scale-in). Shares the
        SetRole lock and epoch fence, so a retire racing a role flip
        resolves to exactly one winner — the loser rejects typed
        (RoleTransitionError), never both. The drain reuses the flip
        machinery with reason ``scale_in``: deregister-first, in-flight
        streams finish within the budget or are killed with typed
        ``incomplete:scale_in`` frames that migrate. On completion the
        ``on_retired`` callback (worker main: runtime.shutdown) fires.
        """
        epoch = int(epoch)
        if self.state == RoleState.RETIRED:
            if epoch == self.applied_epoch:
                return {"action": "retire", "epoch": epoch,
                        "outcome": "duplicate", "state": self.state}
            raise RoleTransitionError("worker is already retired")
        if self._lock.locked():
            if self.target_role is None and self._inflight_epoch == epoch:
                # Duplicate of the running retire: acknowledged.
                return {"action": "retire", "epoch": epoch,
                        "outcome": "duplicate", "state": self.state}
            self._note_retire_fence(epoch, "rejected_busy", cause=cause)
            raise RoleTransitionError(
                f"transition (epoch {self._inflight_epoch}) in flight; "
                "retire rejected")
        async with self._lock:
            if epoch <= self.applied_epoch:
                self._note_retire_fence(epoch, "rejected_stale", cause=cause)
                raise RoleTransitionError(
                    f"stale retire epoch {epoch} (applied epoch "
                    f"{self.applied_epoch})")
            self._inflight_epoch = epoch
            budget = self.drain_s if drain_s is None else drain_s
            log.info("scale-in retire (epoch %d, by %s): draining up to "
                     "%.1fs", epoch, issued_by, budget)
            requested_ref = journal.emit(
                EventKind.SCALE_RETIRE, cause=cause, phase="draining",
                epoch=epoch, issued_by=issued_by,
                inflight=self.profile.inflight if self.profile else 0,
                drain_s=budget)
            outcome, error = "ok", None
            with span("role.retire", epoch=epoch, issued_by=issued_by) as sp:
                try:
                    self.state = RoleState.DRAINING
                    await self._write_status()
                    if self.profile is not None:
                        with span("role.drain",
                                  inflight=self.profile.inflight):
                            await self.profile.drain(
                                budget, reason=SCALE_IN_REASON)
                        await self.profile.close()
                        self.profile = None
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 — typed outcome
                    outcome = "failed"
                    error = f"{type(exc).__name__}: {exc}"
                    log.exception("scale-in drain failed; retiring anyway")
                finally:
                    self.applied_epoch = epoch
                    self.state = RoleState.RETIRED
                    self._inflight_epoch = None
                    self.last_outcome = {
                        "action": "retire", "epoch": epoch,
                        "outcome": outcome, "ts": time.time(),
                        **({"error": error} if error else {})}
                    journal.emit(EventKind.SCALE_RETIRE, cause=requested_ref,
                                 phase="done", epoch=epoch, outcome=outcome)
                    sp.set(outcome=outcome)
                    await self._write_status()
            log.info("scale-in retire (epoch %d): %s", epoch, outcome)
        if self._on_retired is not None:
            try:
                res = self._on_retired()
                if asyncio.iscoroutine(res):
                    await res
            except Exception:  # noqa: BLE001 — shutdown hook best-effort
                log.exception("on_retired hook failed")
        return self.last_outcome

    def _note_retire_fence(self, epoch: int, outcome: str,
                           cause: str | None = None) -> None:
        self.last_outcome = {"action": "retire", "epoch": epoch,
                             "outcome": outcome, "ts": time.time()}
        journal.emit(EventKind.SCALE_RETIRE, cause=cause, phase="rejected",
                     epoch=epoch, outcome=outcome)
        if self._m_flips is not None:
            self._m_flips.inc(**{"from": self.role, "to": "retired",
                                 "outcome": outcome})

    async def _build_with_retry(self, role: str) -> ServingProfile:
        """Build a profile, riding out coordinator outages: registration
        needs the control plane, and a flip that straddles a coordinator
        restart must converge once it returns (transient transport
        errors only — real build bugs propagate immediately)."""
        backoff = Backoff(policies.COORD_RECONNECT)
        while True:
            try:
                return await self._build(role)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                log.warning("profile build for role %s hit a transport "
                            "error; retrying", role, exc_info=True)
                await backoff.sleep()

    # -- directive watch ------------------------------------------------------
    async def _apply_directive(self, value) -> None:
        if not isinstance(value, dict) or "role" not in value:
            log.warning("malformed role directive ignored: %r", value)
            return
        try:
            await self.set_role(
                str(value["role"]), int(value.get("epoch", 0)),
                issued_by=str(value.get("issued_by", "directive")),
                drain_s=value.get("drain_s"),
                cause=value.get("cause"))
        except RoleTransitionError as exc:
            # Fencing rejections are normal under replay/duplication;
            # the typed decision is visible in status/metrics.
            log.info("role directive fenced out: %s", exc)
        except (ValueError, TypeError) as exc:
            log.warning("malformed role directive ignored: %s", exc)

    async def _watch_loop(self) -> None:
        """Directive intake. Must survive anything short of cancellation:
        a dead watch loop would strand the worker in its launch role
        forever while the planner keeps (re)issuing flips."""
        backoff = Backoff(policies.COORD_RECONNECT)
        while True:
            try:
                async for event in self._watch:
                    if event["event"] == "put":
                        await self._apply_directive(event["value"])
                    # delete = issuer revoked (or its lease died). An
                    # un-started directive simply never applies; a
                    # running flip converges forward — both consistent.
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — re-establish, never die
                log.exception("role directive watch failed; re-watching")
            await backoff.sleep()
            try:
                self._watch = await self._runtime.require_coordinator() \
                    .watch_prefix(role_key(self.namespace,
                                           self._runtime.instance_id))
                for item in self._watch.snapshot:
                    await self._apply_directive(item["v"])
                backoff.reset()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("role directive re-watch failed; will retry")

    # -- status ---------------------------------------------------------------
    def status(self) -> dict:
        """The worker's role status (also the coordinator payload and the
        status server's GET /control/role body)."""
        return {
            "worker": f"{self._runtime.instance_id:x}",
            "role": self.role,
            "state": self.state,
            "epoch": self.applied_epoch,
            "target_role": self.target_role,
            "inflight": self.profile.inflight if self.profile else 0,
            "flips": self.flips,
            "last_outcome": self.last_outcome,
            "ts": time.time(),
            **self._extra,
        }

    async def _write_status(self) -> None:
        """Best-effort status publish (worker's primary lease). A flip
        must not wedge on a coordinator outage: the lease-recreated
        callback replays the final state after reconnect."""
        if not self._runtime.has_discovery:
            return
        try:
            await self._runtime.require_coordinator().kv_put(
                role_status_key(self.namespace, self._runtime.instance_id),
                self.status(), use_primary_lease=True)
        except (ConnectionError, OSError, RuntimeError):
            log.warning("role status write failed (coordinator down?); "
                        "will replay on reconnect")

    def _outcome(self, old: str, new: str, epoch: int, outcome: str,
                 error: str | None = None) -> dict:
        rec = {"from": old, "to": new, "epoch": epoch, "outcome": outcome,
               "ts": time.time()}
        if error:
            rec["error"] = error
        return rec

    def _note_fence(self, old: str, new: str, epoch: int,
                    outcome: str, cause: str | None = None) -> None:
        self.last_outcome = self._outcome(old, new, epoch, outcome)
        journal.emit(EventKind.ROLE_FLIP_REJECTED, cause=cause,
                     **{"from": old, "to": new, "epoch": epoch,
                        "outcome": outcome})
        if self._m_flips is not None:
            self._m_flips.inc(**{"from": old, "to": new, "outcome": outcome})

    def _set_role_gauge(self) -> None:
        if self._m_role is None:
            return
        for r in ROLES:
            self._m_role.set(1.0 if r == self.role else 0.0, role=r)
