"""Request/response protocol types.

Capability parity with reference lib/llm/src/protocols (OpenAI types +
``common.rs`` internal types): the OpenAI-facing models are pydantic (request
validation at the HTTP edge, protocols/openai/*), while the internal
frontend<->worker contract — PreprocessedRequest and LLMEngineOutput
(protocols/common.rs:811, common/llm_backend.rs) — travels as plain dicts over
msgpack frames.
"""

from __future__ import annotations

import time
import uuid
from enum import Enum
from typing import Any, Literal

from pydantic import BaseModel, ConfigDict, Field, model_validator


# ---------------------------------------------------------------------------
# Internal types (reference protocols/common.rs)
# ---------------------------------------------------------------------------

class FinishReason(str, Enum):
    """Reference FinishReason (protocols/common.rs)."""

    STOP = "stop"            # stop string / stop token matched
    EOS = "eos"              # model emitted EOS
    LENGTH = "length"        # max_tokens reached
    CANCELLED = "cancelled"  # client disconnected / ctx stopped
    ERROR = "error"

    def to_openai(self) -> str:
        return {"eos": "stop", "cancelled": "stop"}.get(self.value, self.value)


class StopConditions(BaseModel):
    """Reference common.rs StopConditions."""

    max_tokens: int | None = None
    min_tokens: int | None = None
    stop: list[str] = Field(default_factory=list)
    stop_token_ids: list[int] = Field(default_factory=list)
    ignore_eos: bool = False


class SamplingOptions(BaseModel):
    """Reference common.rs SamplingOptions."""

    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    frequency_penalty: float | None = None
    presence_penalty: float | None = None
    seed: int | None = None
    n: int = 1
    logprobs: int | None = None


class PreprocessedRequest(BaseModel):
    """Tokens-in request: the frontend->worker contract
    (reference preprocessor.rs:92 output, protocols/common.rs)."""

    model: str
    token_ids: list[int]
    stop_conditions: StopConditions = Field(default_factory=StopConditions)
    sampling_options: SamplingOptions = Field(default_factory=SamplingOptions)
    eos_token_ids: list[int] = Field(default_factory=list)
    annotations: dict[str, Any] = Field(default_factory=dict)
    # Multi-tenant LoRA (engine/lora.py): the adapter name this request
    # forwards through, resolved by the frontend from the served model
    # card (the OpenAI ``model`` field names an adapter slug whose card
    # points at the base worker). None = base model. The worker maps it
    # to a resident device slot at admission (hot-loading on miss).
    adapter: str | None = None
    # Disaggregation: router-to-worker hints (reference kv_transfer_params).
    disagg_params: dict[str, Any] | None = None
    # Router-estimated prefix-cache overlap, for engine scheduling.
    estimated_prefix_hit_blocks: int = 0
    # Multimodal prompt embeddings (the reference's multimodal processor
    # role, components/backends/trtllm multimodal): spans of token_ids
    # whose embeddings come from a modality encoder instead of the token
    # table. Each: {"start": int, "b": bytes, "dtype": str,
    # "shape": [n, hidden]} — the placeholder token ids under a span are
    # ignored by the forward pass.
    mm_embeds: list[dict] | None = None

    def to_wire(self) -> dict:
        return self.model_dump(exclude_none=True)

    @classmethod
    def from_wire(cls, data: dict) -> "PreprocessedRequest":
        return cls.model_validate(data)


class LLMEngineOutput(BaseModel):
    """One streamed engine response (reference common/llm_backend.rs)."""

    token_ids: list[int] = Field(default_factory=list)
    text: str | None = None  # filled by the detokenizing Backend operator
    finish_reason: FinishReason | None = None
    cum_log_prob: float | None = None
    log_probs: list[float] | None = None
    # Per token: top alternatives [{token_id, logprob, token?}] (token text
    # filled by the detokenizing Backend operator).
    top_log_probs: list[list[dict[str, Any]]] | None = None
    # Per-token decoded strings (filled by Backend when log_probs present;
    # the OpenAI logprobs block needs per-token text, not just the delta).
    token_texts: list[str] | None = None
    # Per-stream metrics annotation (reference LLMMetricAnnotation,
    # preprocessor.rs:58): first-token flag etc.
    metrics: dict[str, Any] | None = None
    # kv transfer results for disaggregated prefill responses.
    disagg_params: dict[str, Any] | None = None

    def to_wire(self) -> dict:
        return self.model_dump(exclude_none=True)

    @classmethod
    def from_wire(cls, data: dict) -> "LLMEngineOutput":
        return cls.model_validate(data)


# ---------------------------------------------------------------------------
# OpenAI API types (reference protocols/openai + vendored async-openai)
# ---------------------------------------------------------------------------

class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: str | list[dict[str, Any]] | None = None
    name: str | None = None
    tool_calls: list[dict[str, Any]] | None = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        return "".join(p.get("text", "") for p in self.content
                       if p.get("type") == "text")


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: list[ChatMessage]
    max_tokens: int | None = None
    max_completion_tokens: int | None = None
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None  # extension (nvext-style)
    n: int = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    stop: str | list[str] | None = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    seed: int | None = None
    logprobs: bool | None = None
    top_logprobs: int | None = None
    ignore_eos: bool | None = None  # extension
    min_tokens: int | None = None  # extension
    # Reference nvext extension block (vendored async-openai's NvExt,
    # lib/llm/src/protocols/openai/nvext.rs role): same knobs nested
    # under "nvext" for clients written against the reference API. Flat
    # fields win when both are set. Lifted BEFORE validation so nvext
    # values get full pydantic coercion/422s, not raw setattr.
    nvext: dict[str, Any] | None = None

    @model_validator(mode="before")
    @classmethod
    def _merge_nvext(cls, data):
        if isinstance(data, dict) and isinstance(data.get("nvext"), dict):
            for key in ("ignore_eos", "top_k", "min_tokens", "seed",
                        "frequency_penalty", "presence_penalty"):
                if data.get(key) is None and key in data["nvext"]:
                    data[key] = data["nvext"][key]
        return data

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: str | list[str] | list[int]
    max_tokens: int | None = 16
    temperature: float | None = None
    top_p: float | None = None
    n: int = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    stop: str | list[str] | None = None
    seed: int | None = None
    echo: bool = False
    ignore_eos: bool | None = None
    nvext: dict[str, Any] | None = None  # reference NvExt block

    @model_validator(mode="before")
    @classmethod
    def _merge_nvext(cls, data):
        if isinstance(data, dict) and isinstance(data.get("nvext"), dict):
            for key in ("ignore_eos", "seed", "min_tokens"):
                if data.get(key) is None and key in data["nvext"]:
                    data[key] = data["nvext"][key]
        return data
    min_tokens: int | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: str | list[str] | list[int] | list[list[int]]
    encoding_format: Literal["float", "base64"] = "float"


def completion_id() -> str:
    return "cmpl-" + uuid.uuid4().hex[:24]


def chat_completion_id() -> str:
    return "chatcmpl-" + uuid.uuid4().hex[:24]


def now_unix() -> int:
    return int(time.time())


def usage_block(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }
