"""Queue-based prefill dispatch (the reference's JetStream PrefillQueue,
lib/runtime/src/transports/nats.rs:433-600 NatsQueue + the xPyD
load-leveling described in docs/architecture/disagg_serving.md).

Instead of the decode worker round-robining prompts at prefill workers
(direct mode, llm/disagg.py), it PUSHES work onto a shared coordinator
queue and prefill workers PULL when free — a worker chewing a long
prompt simply doesn't pull, so load levels across xP automatically.

Flow: decode worker subscribes a per-request reply subject, pushes
{req, reply} onto ``prefillq/<model>``, and waits (bounded). A prefill
worker's pull loop pops, prefills + stages the KV parcel on its data
plane (llm/kv_plane.py), and publishes {ticket, first_token} to the
reply subject; the decode worker pulls the parcel worker-to-worker and
injects. Queue DEPTH is the backpressure signal: past
``max_queue_depth`` the decode worker prefills locally instead of
enqueueing (the queue-depth-driven local/remote split — conditional
disaggregation's load-leveling term).
"""

from __future__ import annotations

import asyncio
import time
import uuid

from dynamo_tpu.llm.model_card import model_slug
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, policies
from dynamo_tpu.runtime.tracing import get_recorder, span

log = get_logger("prefill_queue")

REPLY_PREFIX = "prefillr."


def queue_name(model_name: str) -> str:
    return f"prefillq/{model_slug(model_name)}"


class QueuePrefillWorker:
    """Prefill-worker side: pull -> prefill+stage -> reply, one at a time
    (pulling only when free IS the load-leveling — a busy worker leaves
    work on the queue for its peers)."""

    def __init__(self, engine, client, model_name: str, plane,
                 poll_timeout: float = 1.0):
        self.engine = engine
        self.client = client
        self.queue = queue_name(model_name)
        self.plane = plane
        self.poll_timeout = poll_timeout
        self.pulled = 0
        self.failed = 0
        self._paused = False
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    def pause(self) -> None:
        """Stop pulling NEW queue work (a draining worker — role flip or
        retire — must leave queued prompts to its peers; the item being
        served finishes normally). Idempotent."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                # The awaited task finishing as cancelled is the expected
                # outcome of our own .cancel() above. If stop() itself was
                # cancelled, the current task is still marked, so the next
                # await re-raises — swallowing here does not absorb it.
                pass
            except Exception:  # noqa: BLE001 — already torn down
                pass

    async def _loop(self) -> None:
        backoff = Backoff(policies.QUEUE_POP)
        while True:
            if self._paused:
                await asyncio.sleep(self.poll_timeout)
                continue
            try:
                item = await self.client.queue_pop(
                    self.queue, timeout=self.poll_timeout)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the pull loop must survive
                # Anything less and the worker silently stops draining the
                # queue while still serving the direct endpoint — queue-
                # mode decode workers would degrade to local-only forever.
                log.exception("prefill queue pop failed; retrying")
                await backoff.sleep()
                continue
            backoff.reset()
            if item is None:
                continue
            await self._serve_one(item)

    async def _serve_one(self, item: dict) -> None:
        reply = item.get("reply")
        try:
            req = PreprocessedRequest.from_wire(item["req"])
            # The dispatcher's trace context rides the queue item, so the
            # queue hop shows up in the request's distributed trace. The
            # dequeue-wait span uses the enqueue wall timestamp (same
            # clock domain is fine for the in-cluster case this serves).
            ctx = Context.from_wire(item.get("ctx"))
            rec = get_recorder()
            if rec.enabled and item.get("t_enq"):
                waited = max(0.0, time.time() - item["t_enq"])
                now = time.monotonic()
                rec.add("prefill_queue.wait", ctx.trace_id,
                        ctx.parent_span_id, now - waited, now,
                        attrs={"queue": self.queue})
            with span("prefill_queue.serve", ctx=ctx,
                      queue=self.queue) as sp:
                first_token, ticket, prompt_len = await self.engine.run_job(
                    lambda: self.engine.prefill_extract_staged(
                        req, self.plane))
                sp.set(prompt_tokens=prompt_len,
                       nbytes=int(ticket.get("nbytes", 0)))
            self.pulled += 1
            log.info("queue prefill served: %d tokens, ticket %d",
                     prompt_len, ticket["id"])
            await self.client.publish(
                reply, {"first_token": first_token, "ticket": ticket})
        except Exception as exc:  # noqa: BLE001 — report to the requester
            self.failed += 1
            log.exception("queue prefill failed")
            if reply:
                try:
                    await self.client.publish(reply, {"error": str(exc)})
                except (ConnectionError, OSError):
                    pass


class QueuePrefillDispatcher:
    """Decode-worker side: enqueue with depth backpressure, await the
    reply, pull the parcel over the data plane."""

    def __init__(self, client, model_name: str, plane_client,
                 max_queue_depth: int = 8, reply_timeout: float = 120.0):
        self.client = client
        self.queue = queue_name(model_name)
        self.plane_client = plane_client
        self.max_queue_depth = max_queue_depth
        self.reply_timeout = reply_timeout
        self.enqueued = 0
        self.backpressured = 0

    async def remote_prefill(self, req: PreprocessedRequest,
                             context: Context | None = None):
        """Returns (first_token, kv) or None (backpressure/timeout/error —
        caller prefills locally). ``context`` threads the request's trace
        onto the queue item so the prefill worker's spans join it."""
        depth = await self.client.queue_len(self.queue)
        if depth >= self.max_queue_depth:
            # The queue-depth-driven prefill-load split: deep queue means
            # every prefill worker is busy — local prefill beats queueing.
            self.backpressured += 1
            log.info("prefill queue depth %d >= %d: prefilling locally",
                     depth, self.max_queue_depth)
            return None
        reply = REPLY_PREFIX + uuid.uuid4().hex
        sub = await self.client.subscribe(reply)
        try:
            with span("prefill_queue.dispatch", ctx=context,
                      queue=self.queue, depth=depth) as sp:
                item = {"req": req.to_wire(), "reply": reply,
                        "t_enq": time.time()}
                if context is not None:
                    item["ctx"] = context.to_wire()
                await self.client.queue_push(self.queue, item)
                self.enqueued += 1
                try:
                    msg = await asyncio.wait_for(
                        sub.__aiter__().__anext__(),
                        timeout=self.reply_timeout)
                except asyncio.TimeoutError:
                    log.warning("prefill queue reply timed out after %.0fs",
                                self.reply_timeout)
                    return None
                payload = msg["payload"]
                if "error" in payload:
                    log.warning("queued prefill failed remotely: %s",
                                payload["error"])
                    return None
                kv = await self.plane_client.pull(payload["ticket"])
                sp.set(nbytes=int(kv.nbytes))
                return payload["first_token"], kv
        finally:
            await sub.cancel()
