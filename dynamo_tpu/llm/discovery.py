"""Model discovery: watcher + manager + routed pipeline assembly.

Capability parity with reference ModelWatcher/ModelManager (lib/llm/src/
discovery/watcher.rs:46-93, model_manager.rs) and build_routed_pipeline
(entrypoint/input/common.rs:216-265): watch the models/ KV prefix; on the first
instance of a model, fetch its tokenizer from the object store and assemble
  Preprocessor -> Backend(detokenize) -> Migration -> Router(client)
; on lease-expiry deletes, drop the model when its last instance vanishes.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.model_card import MODEL_ROOT, ModelEntry, fetch_tokenizer
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import span

log = get_logger("discovery")


class RouterEngine(AsyncEngine):
    """Pipeline sink: pushes the preprocessed request to a worker instance via
    the request plane (reference ServiceBackend + PushRouter link,
    common.rs:258-265). router_mode 'kv' is layered in kv_router."""

    def __init__(self, client, router_mode: str = "round_robin"):
        self.client = client
        self.router_mode = router_mode

    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        with span("router.decide", mode=self.router_mode):
            stream = await self.client.generate(
                request if isinstance(request, dict) else request.to_wire(),
                context=context, mode=self.router_mode)
        async for item in stream:
            yield item


class ServedModel:
    """One routable model: its entry, tokenizer-bound pipeline, and client."""

    def __init__(self, entry: ModelEntry, preprocessor: OpenAIPreprocessor,
                 client, router):
        self.entry = entry
        self.preprocessor = preprocessor
        self.client = client
        self.router = router
        self.instances: set[int] = set()

    @property
    def name(self) -> str:
        return self.entry.model_name


class ModelManager:
    """Holds the set of currently-servable models (reference
    discovery/model_manager.rs)."""

    def __init__(self):
        self.models: dict[str, ServedModel] = {}

    def get(self, name: str) -> ServedModel | None:
        return self.models.get(name)

    def list_models(self) -> list[dict]:
        return [{"id": m.name, "object": "model", "created": 0,
                 "owned_by": "dynamo-tpu"} for m in self.models.values()]


class ModelWatcher:
    def __init__(self, runtime, manager: ModelManager,
                 router_mode: str = "round_robin",
                 kv_router_factory=None, store=None):
        self._runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self._kv_router_factory = kv_router_factory
        # Fleet-membership hooks, called as (served, instance_id) after
        # the join/leave journal event: the canary prober gates joins
        # through them (llm/canary.py note_join/note_leave).
        self.on_join = None
        self.on_leave = None
        # Storage-pluggable discovery plane (reference key_value_store.rs
        # trait): any runtime.storage.KeyValueStore carries the model-entry
        # watch and tokenizer artifacts; default is the coordinator.
        # Endpoint *connectivity* still comes from the runtime — a
        # local store swaps out the config/discovery plane, not the
        # request plane.
        self._store = store
        # KV routers shared across served names that point at the SAME
        # worker endpoint — LoRA adapter cards ride their base model's
        # workers, and a per-name router would split the radix/fleet
        # view (and the breaker state) that makes KV-aware routing work.
        # Keyed by (namespace, component, endpoint); refcounted by the
        # model names using it so the last leaver closes it.
        self._router_share: dict[tuple, dict] = {}
        self._task: asyncio.Task | None = None
        self._watch = None
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        client = self._store or self._runtime.require_coordinator()
        self._watch = await client.watch_prefix(MODEL_ROOT)
        for item in self._watch.snapshot:
            await self._on_put(item["k"], item["v"])
        self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        async for event in self._watch:
            try:
                if event["event"] == "put":
                    await self._on_put(event["key"], event["value"])
                else:
                    await self._on_delete(event["key"])
            except Exception:  # noqa: BLE001
                log.exception("model watch event failed")

    async def _on_put(self, key: str, value: dict) -> None:
        entry = ModelEntry.from_wire(value)
        instance_hex = key.rsplit("/", 1)[-1]
        async with self._lock:
            served = self.manager.models.get(entry.model_name)
            if served is None:
                served = await self._build(entry)
                self.manager.models[entry.model_name] = served
                log.info("model %s now served via %s/%s/%s", entry.model_name,
                         entry.namespace, entry.component, entry.endpoint)
            try:
                iid = int(instance_hex, 16)
            except ValueError:
                return
            if iid not in served.instances:
                served.instances.add(iid)
                # Decision plane: fleet membership changes are the raw
                # material of most incident chains ("the flip happened
                # because the fleet lost a worker"). A join caused by a
                # standby promotion names it when the promote event
                # already reached this process's timeline collector.
                journal.emit(EventKind.WORKER_JOIN, model=entry.model_name,
                             instance=instance_hex)
                if self.on_join is not None:
                    try:
                        self.on_join(served, iid)
                    except Exception:  # noqa: BLE001 — a hook, not a gate
                        log.exception("on_join hook failed")

    async def _on_delete(self, key: str) -> None:
        parts = key[len(MODEL_ROOT):].split("/")
        if len(parts) != 2:
            return
        slug, instance_hex = parts
        async with self._lock:
            for name, served in list(self.manager.models.items()):
                from dynamo_tpu.llm.model_card import model_slug
                if model_slug(name) != slug:
                    continue
                try:
                    iid = int(instance_hex, 16)
                except ValueError:
                    iid = None
                if iid is not None and iid in served.instances:
                    served.instances.discard(iid)
                    # A lease-expiry delete under chaos is chaos's doing.
                    from dynamo_tpu.runtime import chaos
                    journal.emit(
                        EventKind.WORKER_LEAVE,
                        cause=(journal.recent_ref(EventKind.CHAOS_INJECT)
                               if chaos.ACTIVE else None),
                        model=name, instance=instance_hex)
                    # Fleet-membership pruning beats staleness TTLs: the
                    # KV router drops the worker's inventory/index and
                    # breaker state NOW (scale-in must not leave ghosts
                    # that attract routing for 30 more seconds).
                    note_leave = getattr(served.router,
                                         "note_worker_leave", None)
                    if note_leave is not None:
                        note_leave(iid)
                    if self.on_leave is not None:
                        try:
                            self.on_leave(served, iid)
                        except Exception:  # noqa: BLE001
                            log.exception("on_leave hook failed")
                if not served.instances:
                    log.info("model %s: last instance gone; removing", name)
                    await self._close_served(served)
                    del self.manager.models[name]

    async def _close_served(self, served: ServedModel) -> None:
        for key, share in list(self._router_share.items()):
            if share["router"] is served.router:
                share["users"].discard(served.name)
                if share["users"]:
                    return  # other served names (adapters/base) still use it
                del self._router_share[key]
                break
        router_close = getattr(served.router, "close", None)
        if router_close is not None:
            await router_close()  # also closes the underlying client
        else:
            await served.client.close()

    async def _build(self, entry: ModelEntry) -> ServedModel:
        store = self._store or self._runtime.require_coordinator()
        tokenizer = await fetch_tokenizer(store, entry.card)
        endpoint = (self._runtime.namespace(entry.namespace)
                    .component(entry.component).endpoint(entry.endpoint))
        if self.router_mode == "kv" and self._kv_router_factory is not None:
            share_key = (entry.namespace, entry.component, entry.endpoint)
            share = self._router_share.get(share_key)
            if share is None:
                client = await endpoint.client()
                router = await self._kv_router_factory(self._runtime, entry,
                                                       client)
                share = {"router": router, "client": client, "users": set()}
                self._router_share[share_key] = share
            client, router = share["client"], share["router"]
            share["users"].add(entry.model_name)
        else:
            client = await endpoint.client()
            router = RouterEngine(client, self.router_mode)
        chain = Migration(entry.card.migration_limit, inner=router,
                          metrics=self._runtime.metrics)
        backend = Backend(tokenizer, inner=chain)
        preprocessor = OpenAIPreprocessor(entry.card, tokenizer, inner=backend)
        return ServedModel(entry, preprocessor, client, router)

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._watch:
            await self._watch.cancel()
        for served in list(self.manager.models.values()):
            await self._close_served(served)
        self.manager.models.clear()
