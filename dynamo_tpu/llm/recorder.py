"""Stream recording + JSONL event recorder.

Capability parity with reference perf.rs (TimestampedResponse,
RecordedStream, record_stream — perf.rs:32-137) and recorder.rs (Recorder:
an mpsc-fed background task appending JSONL — recorder.rs:26-256): capture
response streams with arrival timestamps for offline latency analysis, and
durably log events to JSONL without blocking the hot path.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
from typing import Any, AsyncIterator


@dataclasses.dataclass
class TimestampedResponse:
    """One captured stream item (perf.rs:32)."""
    data: Any
    sequence: int
    t: float  # seconds since the stream's start

    def to_wire(self) -> dict:
        return {"t": self.t, "seq": self.sequence, "data": self.data}


class RecordedStream:
    """A fully-captured response stream with timing analytics
    (perf.rs:84-130)."""

    def __init__(self, responses: list[TimestampedResponse],
                 start_time: float, end_time: float):
        self.responses = responses
        self.start_time = start_time
        self.end_time = end_time

    @property
    def response_count(self) -> int:
        return len(self.responses)

    @property
    def total_duration_s(self) -> float:
        return self.end_time - self.start_time

    def ttft_s(self) -> float | None:
        """Time to the first item carrying tokens (or any first item)."""
        for r in self.responses:
            data = r.data if isinstance(r.data, dict) else {}
            if data.get("token_ids") or not isinstance(r.data, dict):
                return r.t
        return self.responses[0].t if self.responses else None

    def inter_arrival_s(self) -> list[float]:
        ts = [r.t for r in self.responses]
        return [b - a for a, b in zip(ts, ts[1:])]

    def token_count(self) -> int:
        n = 0
        for r in self.responses:
            if isinstance(r.data, dict):
                n += len(r.data.get("token_ids") or [])
        return n

    def analytics(self) -> dict:
        gaps = sorted(self.inter_arrival_s())
        return {
            "responses": self.response_count,
            "tokens": self.token_count(),
            "duration_s": self.total_duration_s,
            "ttft_s": self.ttft_s(),
            "itl_mean_s": (sum(gaps) / len(gaps)) if gaps else None,
            "itl_p99_s": gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))]
            if gaps else None,
        }

    def to_wire(self) -> dict:
        return {"start": self.start_time, "end": self.end_time,
                "responses": [r.to_wire() for r in self.responses]}


async def record_stream(stream: AsyncIterator,
                        passthrough: bool = False):
    """Consume (or tee) a stream into a RecordedStream (perf.rs
    record_stream). With passthrough=False, returns the RecordedStream;
    with passthrough=True, returns an async generator yielding items while
    recording — read `.recorded` after exhaustion."""
    if not passthrough:
        start = time.monotonic()
        items: list[TimestampedResponse] = []
        i = 0
        async for item in stream:
            items.append(TimestampedResponse(item, i,
                                             time.monotonic() - start))
            i += 1
        return RecordedStream(items, 0.0, time.monotonic() - start)

    holder = _RecordingTee(stream)
    return holder


class _RecordingTee:
    def __init__(self, stream: AsyncIterator):
        self._stream = stream
        self.recorded: RecordedStream | None = None

    def __aiter__(self):
        return self._iter()

    async def _iter(self):
        start = time.monotonic()
        items: list[TimestampedResponse] = []
        i = 0
        try:
            async for item in self._stream:
                items.append(TimestampedResponse(item, i,
                                                 time.monotonic() - start))
                i += 1
                yield item
        finally:
            self.recorded = RecordedStream(items, 0.0,
                                           time.monotonic() - start)


class Recorder:
    """JSONL event recorder (recorder.rs:26): events enqueue without
    blocking; a background task appends them to the file, flushing per
    batch. Call ``close`` to drain."""

    def __init__(self, path: str, queue_size: int = 4096):
        self.path = path
        self._q: asyncio.Queue = asyncio.Queue(maxsize=queue_size)
        self._task: asyncio.Task | None = None
        self._closed = False
        self.dropped = 0
        self.written = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    def record(self, event: dict) -> None:
        """Non-blocking enqueue; drops (and counts) when the sink can't
        keep up rather than stalling the serving path."""
        if self._closed:
            return
        try:
            self._q.put_nowait({"ts": time.time(), **event})
        except asyncio.QueueFull:
            self.dropped += 1

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        # open() shares the disk-I/O exile with the writes: on a hung NFS
        # mount even the open can stall the loop for seconds.
        fh = await loop.run_in_executor(None, open, self.path, "a")
        try:
            while True:
                event = await self._q.get()
                stop = event is None
                batch = [] if stop else [event]
                while not self._q.empty():
                    nxt = self._q.get_nowait()
                    if nxt is None:
                        stop = True
                        break
                    batch.append(nxt)
                if batch:
                    # Disk writes off the event loop: a contended disk must
                    # not stall token streaming or lease keepalives.
                    def write_batch(batch=batch):
                        for e in batch:
                            fh.write(json.dumps(e) + "\n")
                        fh.flush()
                    await loop.run_in_executor(None, write_batch)
                    self.written += len(batch)
                if stop:
                    return
        finally:
            await loop.run_in_executor(None, fh.close)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._task is not None:
            await self._q.put(None)
            await self._task
            self._task = None
