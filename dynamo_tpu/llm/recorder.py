"""Stream recording, JSONL event recorder, per-request accounting.

Capability parity with reference perf.rs (TimestampedResponse,
RecordedStream, record_stream — perf.rs:32-137) and recorder.rs (Recorder:
an mpsc-fed background task appending JSONL — recorder.rs:26-256): capture
response streams with arrival timestamps for offline latency analysis, and
durably log events to JSONL without blocking the hot path.

On top of that, ``RequestLedger``: one structured accounting record per
finished OR shed request (tenant/priority, token counts, queue wait,
TTFT, per-request ITL percentiles, worker id, migrations, typed shed
reason, brownout level, trace id) in a bounded in-memory ring with an
optional JSONL sink that reuses ``Recorder``'s non-blocking appender —
served at ``/debug/requests`` (runtime/health.py) and rolled up offline
by ``scripts/slo_report.py``. The overload invariant extends into the
accounting stream: every shed or failed request still produces a record
with a typed reason — zero silent drops (asserted in
tests/test_overload.py).
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import json
import threading
import time
from typing import Any, AsyncIterator


@dataclasses.dataclass
class TimestampedResponse:
    """One captured stream item (perf.rs:32)."""
    data: Any
    sequence: int
    t: float  # seconds since the stream's start

    def to_wire(self) -> dict:
        return {"t": self.t, "seq": self.sequence, "data": self.data}


class RecordedStream:
    """A fully-captured response stream with timing analytics
    (perf.rs:84-130)."""

    def __init__(self, responses: list[TimestampedResponse],
                 start_time: float, end_time: float):
        self.responses = responses
        self.start_time = start_time
        self.end_time = end_time

    @property
    def response_count(self) -> int:
        return len(self.responses)

    @property
    def total_duration_s(self) -> float:
        return self.end_time - self.start_time

    def ttft_s(self) -> float | None:
        """Time to the first item carrying tokens (or any first item)."""
        for r in self.responses:
            data = r.data if isinstance(r.data, dict) else {}
            if data.get("token_ids") or not isinstance(r.data, dict):
                return r.t
        return self.responses[0].t if self.responses else None

    def inter_arrival_s(self) -> list[float]:
        ts = [r.t for r in self.responses]
        return [b - a for a, b in zip(ts, ts[1:])]

    def token_count(self) -> int:
        n = 0
        for r in self.responses:
            if isinstance(r.data, dict):
                n += len(r.data.get("token_ids") or [])
        return n

    def analytics(self) -> dict:
        gaps = sorted(self.inter_arrival_s())
        return {
            "responses": self.response_count,
            "tokens": self.token_count(),
            "duration_s": self.total_duration_s,
            "ttft_s": self.ttft_s(),
            "itl_mean_s": (sum(gaps) / len(gaps)) if gaps else None,
            "itl_p99_s": gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))]
            if gaps else None,
        }

    def to_wire(self) -> dict:
        return {"start": self.start_time, "end": self.end_time,
                "responses": [r.to_wire() for r in self.responses]}


async def record_stream(stream: AsyncIterator,
                        passthrough: bool = False):
    """Consume (or tee) a stream into a RecordedStream (perf.rs
    record_stream). With passthrough=False, returns the RecordedStream;
    with passthrough=True, returns an async generator yielding items while
    recording — read `.recorded` after exhaustion."""
    if not passthrough:
        start = time.monotonic()
        items: list[TimestampedResponse] = []
        i = 0
        async for item in stream:
            items.append(TimestampedResponse(item, i,
                                             time.monotonic() - start))
            i += 1
        return RecordedStream(items, 0.0, time.monotonic() - start)

    holder = _RecordingTee(stream)
    return holder


class _RecordingTee:
    def __init__(self, stream: AsyncIterator):
        self._stream = stream
        self.recorded: RecordedStream | None = None

    def __aiter__(self):
        return self._iter()

    async def _iter(self):
        start = time.monotonic()
        items: list[TimestampedResponse] = []
        i = 0
        try:
            async for item in self._stream:
                items.append(TimestampedResponse(item, i,
                                                 time.monotonic() - start))
                i += 1
                yield item
        finally:
            self.recorded = RecordedStream(items, 0.0,
                                           time.monotonic() - start)


class Recorder:
    """JSONL event recorder (recorder.rs:26): events enqueue without
    blocking; a background task appends them to the file, flushing per
    batch. Call ``close`` to drain."""

    def __init__(self, path: str, queue_size: int = 4096):
        self.path = path
        self._q: asyncio.Queue = asyncio.Queue(maxsize=queue_size)
        self._task: asyncio.Task | None = None
        self._closed = False
        self.dropped = 0
        self.written = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    def record(self, event: dict) -> None:
        """Non-blocking enqueue; drops (and counts) when the sink can't
        keep up rather than stalling the serving path."""
        if self._closed:
            return
        try:
            self._q.put_nowait({"ts": time.time(), **event})
        except asyncio.QueueFull:
            self.dropped += 1

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        # open() shares the disk-I/O exile with the writes: on a hung NFS
        # mount even the open can stall the loop for seconds.
        fh = await loop.run_in_executor(None, open, self.path, "a")
        try:
            while True:
                event = await self._q.get()
                stop = event is None
                batch = [] if stop else [event]
                while not self._q.empty():
                    nxt = self._q.get_nowait()
                    if nxt is None:
                        stop = True
                        break
                    batch.append(nxt)
                if batch:
                    # Disk writes off the event loop: a contended disk must
                    # not stall token streaming or lease keepalives.
                    def write_batch(batch=batch):
                        for e in batch:
                            fh.write(json.dumps(e) + "\n")
                        fh.flush()
                    await loop.run_in_executor(None, write_batch)
                    self.written += len(batch)
                if stop:
                    return
        finally:
            await loop.run_in_executor(None, fh.close)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._task is not None:
            await self._q.put(None)
            await self._task
            self._task = None


# -- per-request accounting ----------------------------------------------------

#: Record statuses. "shed" carries a typed reason from the overload
#: defense (queue_full/deadline/deadline_wait/priority/no_instances);
#: "error" is a genuine failure (5xx); "cancelled" is a client abort.
ACCOUNT_STATUSES = ("ok", "shed", "error", "cancelled")


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


class RequestLedger:
    """Bounded ring of per-request accounting records + optional JSONL
    sink. ``record()`` is synchronous and non-blocking: the ring append
    happens under a lock, the disk write (when configured) rides the
    ``Recorder`` queue."""

    def __init__(self, capacity: int = 1024, path: str | None = None):
        self.capacity = capacity
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.counts: collections.Counter = collections.Counter()
        self.total = 0
        self._sink: Recorder | None = Recorder(path) if path else None

    def configure_sink(self, path: str | None) -> None:
        self._sink = Recorder(path) if path else None

    def record(self, rec: dict) -> None:
        status = rec.get("status")
        if status not in ACCOUNT_STATUSES:
            rec["status"] = status = "error"
        with self._lock:
            self._ring.append(rec)
            self.counts[status] += 1
            self.total += 1
        sink = self._sink
        if sink is not None:
            try:
                sink.start()  # idempotent; needs a running loop
            except RuntimeError:
                return  # engine-thread caller with no loop: ring only
            sink.record(rec)

    def recent(self, limit: int = 100) -> list[dict]:
        """Newest-first records for /debug/requests."""
        with self._lock:
            snapshot = list(self._ring)
        return snapshot[::-1][:max(0, limit)]

    def snapshot(self, limit: int = 100) -> dict:
        sink = self._sink
        return {
            "capacity": self.capacity,
            "total": self.total,
            "counts": dict(self.counts),
            "sink": ({"path": sink.path, "written": sink.written,
                      "dropped": sink.dropped} if sink else None),
            "records": self.recent(limit),
        }

    async def close(self) -> None:
        if self._sink is not None:
            await self._sink.close()


def make_account(route: str, model: str, ctx=None) -> dict:
    """A fresh accounting record skeleton. The HTTP layer fills in what
    it learns as the request progresses and hands the result to
    ``finish_account``."""
    return {
        "ts": time.time(),
        "route": route,
        "model": model,
        # LoRA adapter the model name resolved to (None = base model):
        # scripts/slo_report.py --by adapter rolls up per-tenant-model
        # TTFT/ITL/token volumes from this field.
        "adapter": None,
        "request_id": getattr(ctx, "id", None),
        "trace_id": getattr(ctx, "trace_id", None),
        "tenant": None,
        "priority": None,
        "deadline_ms": None,
        "status": None,
        "reason": None,
        "http_status": None,
        "prompt_tokens": None,
        "output_tokens": None,
        "reuse_tokens": None,
        "kv_hit_ratio": None,
        # Which tier served the reuse ({"hbm": n, "host": n, "peer": n}
        # prompt tokens): the "was the cache cold, and where" signal.
        "kv_tiers": None,
        "queue_wait_s": None,
        "ttft_s": None,
        "itl_p50_s": None,
        "itl_p99_s": None,
        "duration_s": None,
        "worker_id": None,
        "migrations": 0,
        "migration_reason": None,
        "brownout_level": 0,
        "_t0": time.monotonic(),   # stripped at finish
        "_itls": [],               # raw gaps; folded to p50/p99 at finish
    }


def finish_account(acct: dict, status: str, reason: str | None = None,
                   http_status: int | None = None, ctx=None,
                   ledger: "RequestLedger | None" = None,
                   slo_plane=None) -> dict:
    """Finalize + ledger a record, and feed the SLO availability/goodput
    SLIs from the same event (one instrumentation point, two consumers)."""
    acct["status"] = status
    acct["reason"] = reason
    acct["http_status"] = http_status
    acct["duration_s"] = time.monotonic() - acct.pop("_t0")
    gaps = sorted(acct.pop("_itls"))
    acct["itl_p50_s"] = _percentile(gaps, 0.50)
    acct["itl_p99_s"] = _percentile(gaps, 0.99)
    if ctx is not None:
        values = getattr(ctx, "values", {})
        for key in ("worker_id", "migrations", "migration_reason",
                    "reuse_tokens", "kv_hit_ratio", "kv_tiers",
                    "queue_wait_s", "adapter"):
            if values.get(key) is not None:
                acct[key] = values[key]
    (ledger or get_ledger()).record(acct)
    if slo_plane is not None:
        slo_plane.observe_request(ok=status == "ok", shed=status == "shed")
    return acct


_LEDGER = RequestLedger()


def get_ledger() -> RequestLedger:
    return _LEDGER


def configure_ledger(capacity: int | None = None,
                     path: str | None = None) -> RequestLedger:
    """Entrypoint wiring (SloConfig.request_ring / request_log_path)."""
    global _LEDGER
    if capacity is not None and capacity != _LEDGER.capacity:
        _LEDGER = RequestLedger(capacity, path)
    elif path is not None:
        _LEDGER.configure_sink(path)
    return _LEDGER
