"""Fleet KV/capacity pane: fan out over every worker's status server.

Workers that run a SystemStatusServer register its address under
``system/<namespace>/<instance_hex>`` on the coordinator (lease-bound,
so a dead worker's entry expires with its lease). The frontend's
``GET /debug/fleet`` reads that prefix and fans out ``GET /debug/kv``
(plus ``GET /debug/perf`` for the per-worker perf view) to
every worker — bounded concurrency, a per-worker timeout, and TYPED
partial results: an unreachable worker contributes
``{"ok": false, "error": ...}`` instead of failing the pane, because the
moment an operator needs this view is exactly when part of the fleet is
sick. The merged answer (per-worker allocator/tier/digest + fleet
aggregates) is what the planner and doctor read (docs/OBSERVABILITY.md
"KV & capacity").
"""

from __future__ import annotations

import asyncio
import time

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("fleet")

SYSTEM_ROOT = "system/"

#: Fan-out bounds: the pane is an operator/doctor surface, not a hot
#: path — small concurrency keeps a big fleet's probe from spiking the
#: frontend, the timeout keeps one blackholed worker from stalling it.
DEFAULT_TIMEOUT_S = 2.0
DEFAULT_CONCURRENCY = 8


def system_status_key(namespace: str, instance_id: int) -> str:
    return f"{SYSTEM_ROOT}{namespace}/{instance_id:x}"


async def register_status_server(runtime, port: int,
                                 extra: dict | None = None) -> None:
    """Advertise this worker's status server for the fleet pane. Rides
    the primary lease: deregistration is automatic on death."""
    coordinator = runtime.require_coordinator()
    addr = f"{runtime.advertise_host}:{port}"
    await coordinator.kv_put(
        system_status_key(runtime.config.namespace, runtime.instance_id),
        {"addr": addr, **(extra or {})},
        lease_id=coordinator.primary_lease_id)
    log.info("status server advertised at %s for the fleet pane", addr)


async def _probe_worker(session, sem: asyncio.Semaphore, worker: str,
                        info: dict, timeout_s: float) -> tuple[str, dict]:
    import aiohttp
    addr = info.get("addr")
    base = {"addr": addr, **{k: v for k, v in info.items() if k != "addr"}}
    if not addr:
        return worker, {"ok": False, "error": "no status address "
                        "registered", **base}
    async with sem:
        try:
            async with session.get(
                    f"http://{addr}/debug/kv",
                    timeout=aiohttp.ClientTimeout(total=timeout_s)) as r:
                if r.status != 200:
                    return worker, {"ok": False,
                                    "error": f"HTTP {r.status}", **base}
                res = {"ok": True, "kv": await r.json(), **base}
        except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
            return worker, {"ok": False,
                            "error": f"{type(exc).__name__}: {exc}", **base}
        # Perf view (docs/OBSERVABILITY.md "Engine perf plane"): same
        # status server, typed partial result — a worker predating the
        # perf plane (404) just contributes no "perf" key.
        try:
            async with session.get(
                    f"http://{addr}/debug/perf",
                    timeout=aiohttp.ClientTimeout(total=timeout_s)) as r:
                if r.status == 200:
                    res["perf"] = await r.json()
                elif r.status != 404:
                    res["perf"] = {"error": f"HTTP {r.status}"}
        except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as exc:
            res["perf"] = {"error": f"{type(exc).__name__}: {exc}"}
    return worker, res


def _aggregate(workers: dict[str, dict]) -> dict:
    """Fleet totals over the reachable workers' allocator/tier stats."""
    agg = {"workers_ok": 0, "workers_down": 0, "pages_total": 0,
           "pages_free": 0, "pages_active": 0, "cached_blocks": 0,
           "tier_blocks": {}, "reuse_hit_blocks": 0,
           "reuse_lookup_blocks": 0, "unexpected_recompiles": 0,
           "compiles_total": 0}
    for res in workers.values():
        if not res.get("ok"):
            agg["workers_down"] += 1
            continue
        agg["workers_ok"] += 1
        compiles = (res.get("perf") or {}).get("compiles") or {}
        agg["unexpected_recompiles"] += compiles.get(
            "unexpected_recompiles_total", 0)
        agg["compiles_total"] += compiles.get("compiles_total", 0)
        kv = res.get("kv") or {}
        alloc = kv.get("allocator") or {}
        agg["pages_total"] += alloc.get("pages_total", 0)
        agg["pages_free"] += alloc.get("pages_free", 0)
        agg["pages_active"] += alloc.get("pages_active", 0)
        agg["cached_blocks"] += alloc.get("cached_blocks", 0)
        agg["reuse_hit_blocks"] += alloc.get("reuse_hit_blocks", 0)
        agg["reuse_lookup_blocks"] += alloc.get("reuse_lookup_blocks", 0)
        for tier, n in ((kv.get("digest") or {}).get("tier_blocks")
                        or {}).items():
            agg["tier_blocks"][tier] = agg["tier_blocks"].get(tier, 0) + n
    agg["occupancy"] = (agg["pages_active"] / agg["pages_total"]
                        if agg["pages_total"] else 0.0)
    agg["hit_rate"] = (agg["reuse_hit_blocks"] / agg["reuse_lookup_blocks"]
                       if agg["reuse_lookup_blocks"] else 0.0)
    return agg


async def fleet_kv_snapshot(runtime, namespace: str | None = None,
                            timeout_s: float = DEFAULT_TIMEOUT_S,
                            concurrency: int = DEFAULT_CONCURRENCY,
                            router_view=None) -> dict:
    """The /debug/fleet body. ``router_view`` is the optional local KV
    router's kv_status() callable — merged in so one GET answers both
    "what does each worker hold" and "how cache-aware is routing"."""
    import aiohttp
    ns = namespace or runtime.config.namespace
    t0 = time.monotonic()
    try:
        items = await runtime.require_coordinator().kv_get_prefix(
            f"{SYSTEM_ROOT}{ns}/")
    except (ConnectionError, OSError, RuntimeError) as exc:
        return {"namespace": ns, "error": f"discovery unavailable: {exc}",
                "workers": {}, "partial": True}
    registered = {item["k"].rsplit("/", 1)[-1]: item["v"]
                  for item in items if isinstance(item.get("v"), dict)}
    sem = asyncio.Semaphore(max(1, concurrency))
    workers: dict[str, dict] = {}
    if registered:
        async with aiohttp.ClientSession() as session:
            results = await asyncio.gather(*(
                _probe_worker(session, sem, worker, info, timeout_s)
                for worker, info in sorted(registered.items())))
        workers = dict(results)
    errors = sum(1 for r in workers.values() if not r.get("ok"))
    out = {
        "namespace": ns,
        "workers": workers,
        "partial": errors > 0,
        "errors": errors,
        "aggregate": _aggregate(workers),
        "probe_seconds": round(time.monotonic() - t0, 4),
    }
    if router_view is not None:
        try:
            out["router"] = router_view()
        except Exception as exc:  # noqa: BLE001 — pane stays partial
            out["router"] = {"error": str(exc)}
    return out
