"""Detokenizing backend operator.

Capability parity with reference Backend (lib/llm/src/backend.rs:55-60): a
no-op on the forward (request) edge; on the backward (response) edge it
incrementally detokenizes token_ids into text deltas and enforces stop
sequences — cutting the stream and rewriting the finish reason when a stop
string is matched in decoded text.
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.llm.tokenizer import DecodeStream, StopSequenceChecker, Tokenizer
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine, Operator


class Backend(Operator):
    def __init__(self, tokenizer: Tokenizer, inner: AsyncEngine | None = None):
        super().__init__(inner)
        self.tokenizer = tokenizer

    async def generate(self, request: PreprocessedRequest | dict,
                       context: Context) -> AsyncIterator[LLMEngineOutput]:
        assert self.inner is not None
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        decoder = DecodeStream(self.tokenizer)
        stops = StopSequenceChecker(req.stop_conditions.stop)
        async for raw in self.inner.generate(request, context):
            out = (raw if isinstance(raw, LLMEngineOutput)
                   else LLMEngineOutput.from_wire(raw))
            pieces: list[str] = []
            for tid in out.token_ids:
                delta = decoder.step(tid)
                if delta is not None:
                    pieces.append(delta)
            text = "".join(pieces)
            if out.log_probs is not None:
                # The OpenAI logprobs block needs per-token strings: decode
                # each id standalone (and the top alternatives' ids).
                out.token_texts = [self.tokenizer.decode([tid])
                                   for tid in out.token_ids]
                for alts in out.top_log_probs or []:
                    for alt in alts:
                        alt["token"] = self.tokenizer.decode(
                            [alt["token_id"]])
            if text:
                emit, matched = stops.append(text)
                if matched:
                    # Stop string hit: truncate, finish, and stop the engine.
                    out.text = emit or None
                    out.finish_reason = FinishReason.STOP
                    yield out
                    context.stop_generating()
                    return
                out.text = emit or None
            else:
                out.text = None
            if out.finish_reason is not None:
                # Stream over without a stop match: release any held-back
                # tail (a partial stop-string prefix) so no text is lost.
                held = stops.flush()
                if held:
                    out.text = (out.text or "") + held
            yield out
            if out.finish_reason is not None:
                return
