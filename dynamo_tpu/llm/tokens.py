"""Token-block hashing: the canonical block-hash math.

Capability parity with reference dynamo-tokens (lib/tokens/src/lib.rs:29-370)
and the router's hashing (lib/llm/src/kv_router/indexer.rs:87-150): token
sequences are split into fixed-size blocks; each block's hash chains its
parent's hash (xxh3-64 with a salt), so a block hash uniquely identifies the
entire prefix up to and including that block. Shared by the KV router, the KV
block manager, and engines emitting KV events — all three MUST agree.
"""

from __future__ import annotations

import struct
from typing import Iterable, Sequence

import xxhash

# Salt for hash chaining (reference indexer.rs uses a fixed seed so all
# processes agree).
HASH_SEED = 1337


def hash_block(parent_hash: int | None, token_ids: Sequence[int]) -> int:
    """xxh3_64 over parent hash (8 bytes LE, 0 for the root) + token ids
    (u32 LE each)."""
    h = xxhash.xxh3_64(seed=HASH_SEED)
    h.update(struct.pack("<Q", parent_hash if parent_hash is not None else 0))
    h.update(struct.pack(f"<{len(token_ids)}I", *token_ids))
    return h.intdigest()


def chain_salt(name: str | None) -> int | None:
    """Root-of-chain salt for content that conditions KV beyond the token
    ids themselves — a LoRA adapter name: the same tokens forwarded
    through adapter A produce different K/V than the base model, so
    their block hashes must never alias (engine prefix cache, router
    radix, KV events all chain from this root). None -> unsalted base
    chain, byte-identical to the pre-adapter hash math."""
    if not name:
        return None
    return xxhash.xxh3_64(name.encode(), seed=HASH_SEED).intdigest()


def compute_block_hashes(token_ids: Sequence[int], block_size: int,
                         salt: int | None = None) -> list[int]:
    """Hashes for all COMPLETE blocks of the sequence (partial tail block is
    excluded — it can't be cache-shared; reference
    compute_block_hash_for_seq, indexer.rs:123). ``salt`` (chain_salt)
    roots the chain so adapter-conditioned KV never aliases base KV."""
    hashes: list[int] = []
    parent: int | None = salt
    for start in range(0, len(token_ids) - block_size + 1, block_size):
        parent = hash_block(parent, token_ids[start:start + block_size])
        hashes.append(parent)
    return hashes


class TokenBlockSequence:
    """A token sequence maintained as hashed complete blocks + a partial tail
    (reference TokenBlockSequence/PartialTokenBlock, lib/tokens lib.rs)."""

    def __init__(self, block_size: int, token_ids: Iterable[int] = (),
                 salt: int | None = None):
        self.block_size = block_size
        self.tokens: list[int] = []
        self.block_hashes: list[int] = []
        # Root-of-chain salt (chain_salt): adapter-conditioned sequences
        # hash into a disjoint chain so their KV pages never alias the
        # base model's (or another adapter's) cache entries.
        self.salt = salt
        self.extend(token_ids)

    def extend(self, token_ids: Iterable[int]) -> list[int]:
        """Append tokens; return hashes of any newly completed blocks."""
        self.tokens.extend(token_ids)
        new: list[int] = []
        while len(self.tokens) // self.block_size > len(self.block_hashes):
            idx = len(self.block_hashes)
            block = self.tokens[idx * self.block_size:(idx + 1) * self.block_size]
            parent = self.block_hashes[-1] if self.block_hashes else self.salt
            h = hash_block(parent, block)
            self.block_hashes.append(h)
            new.append(h)
        return new

    def append(self, token_id: int) -> int | None:
        new = self.extend([token_id])
        return new[0] if new else None

    @property
    def num_complete_blocks(self) -> int:
        return len(self.block_hashes)

    def __len__(self) -> int:
        return len(self.tokens)
