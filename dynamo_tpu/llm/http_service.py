"""OpenAI-compatible HTTP service.

Capability parity with reference HttpService (lib/llm/src/http/service/
service_v2.rs:125-340, routers in openai.rs:1023-1094): ``/v1/chat/completions``,
``/v1/completions``, ``/v1/models``, ``/health``, ``/live``, ``/metrics`` with
SSE streaming, client-disconnect cancellation (disconnect.rs), request
validation errors in OpenAI error format, and per-route Prometheus metrics
including TTFT/ITL observations (http/service/metrics.rs).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator

from aiohttp import web
from pydantic import ValidationError

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.preprocessor import aggregate_chat_stream
from dynamo_tpu.llm.protocols import (
    ChatCompletionRequest,
    CompletionRequest,
    usage_block,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.errors import NoInstancesError, OverloadedError
from dynamo_tpu.runtime.logging import get_logger, parse_traceparent

log = get_logger("http")


def _error_body(message: str, err_type: str = "invalid_request_error",
                code: int = 400) -> web.Response:
    return web.Response(
        status=code,
        content_type="application/json",
        text=json.dumps({"error": {"message": message, "type": err_type,
                                   "param": None, "code": None}}))


class HttpService:
    def __init__(self, runtime, manager: ModelManager,
                 host: str = "0.0.0.0", port: int = 8000):
        self._runtime = runtime
        self.manager = manager
        self.host, self.port = host, port
        self._runner: web.AppRunner | None = None
        metrics = runtime.metrics.namespace("http")
        self._m_requests = metrics.counter(
            "http_requests_total", "HTTP requests", ["route", "status"])
        self._m_inflight = metrics.gauge(
            "http_inflight", "In-flight HTTP requests", ["route"])
        self._m_ttft = metrics.histogram(
            "ttft_seconds", "Time to first token", ["model"],
            buckets=[.01, .025, .05, .1, .25, .5, 1, 2.5, 5, 10])
        self._m_itl = metrics.histogram(
            "itl_seconds", "Inter-token latency", ["model"],
            buckets=[.001, .0025, .005, .01, .025, .05, .1, .25, 1])
        self._m_duration = metrics.histogram(
            "http_request_duration_seconds", "Request duration", ["route"])

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._completion)
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("OpenAI HTTP service on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- helpers --------------------------------------------------------------
    def _make_context(self, request: web.Request) -> Context:
        traceparent = request.headers.get("traceparent")
        trace = parse_traceparent(traceparent) if traceparent else None
        ctx = Context(trace_id=trace["trace_id"] if trace else None,
                      parent_span_id=trace["parent_id"] if trace else None)
        return ctx

    async def _sse_stream(self, request: web.Request, chunks: AsyncIterator[dict],
                          ctx: Context, model: str) -> web.StreamResponse:
        # Pull the first chunk BEFORE sending headers so pipeline errors
        # (no instances, overload) still surface as proper HTTP statuses.
        start_t = time.monotonic()
        aiter = chunks.__aiter__()
        try:
            first_chunk = await aiter.__anext__()
        except StopAsyncIteration:
            first_chunk = None
        self._m_ttft.observe(time.monotonic() - start_t, model=model)
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await response.prepare(request)
        last_t = time.monotonic()
        try:
            if first_chunk is not None:
                await response.write(
                    b"data: " + json.dumps(first_chunk).encode() + b"\n\n")
            async for chunk in aiter:
                now = time.monotonic()
                self._m_itl.observe(now - last_t, model=model)
                last_t = now
                await response.write(
                    b"data: " + json.dumps(chunk).encode() + b"\n\n")
            await response.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: propagate kill so the worker frees the slot
            # (reference http/service/disconnect.rs).
            ctx.kill()
            raise
        return response

    # -- routes ---------------------------------------------------------------
    async def _chat(self, request: web.Request) -> web.StreamResponse:
        route = "chat_completions"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        try:
            try:
                body = await request.json()
                chat_req = ChatCompletionRequest.model_validate(body)
            except (json.JSONDecodeError, ValidationError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(chat_req.model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {chat_req.model!r} not found",
                                   "model_not_found", 404)
            ctx = self._make_context(request)
            try:
                chunks = served.preprocessor.generate(chat_req, ctx)
                if chat_req.stream:
                    resp = await self._sse_stream(request, chunks, ctx,
                                                  chat_req.model)
                    self._m_requests.inc(route=route, status="200")
                    return resp
                # Non-streaming: force the usage chunk through the delta
                # stream so the aggregate carries real token counts.
                chat_req.stream_options = {"include_usage": True}
                full = await aggregate_chat_stream(chunks, 0)
                self._m_requests.inc(route=route, status="200")
                return web.json_response(full)
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "service_unavailable", 503)
            except OverloadedError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "overloaded", 503)
            except Exception as exc:  # noqa: BLE001
                log.exception("chat handler failed")
                self._m_requests.inc(route=route, status="500")
                return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _completion(self, request: web.Request) -> web.StreamResponse:
        route = "completions"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        try:
            try:
                body = await request.json()
                comp_req = CompletionRequest.model_validate(body)
            except (json.JSONDecodeError, ValidationError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(comp_req.model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {comp_req.model!r} not found",
                                   "model_not_found", 404)
            ctx = self._make_context(request)
            try:
                if not comp_req.stream:
                    # Force the usage chunk so the folded response has counts.
                    comp_req.stream_options = {"include_usage": True}
                chunks = served.preprocessor.generate_completion(comp_req, ctx)
                if comp_req.stream:
                    resp = await self._sse_stream(request, chunks, ctx,
                                                  comp_req.model)
                    self._m_requests.inc(route=route, status="200")
                    return resp
                texts: list[str] = []
                finish = None
                meta: dict = {}
                usage = None
                async for chunk in chunks:
                    meta = {k: chunk.get(k, meta.get(k))
                            for k in ("id", "created")}
                    if chunk.get("usage"):
                        usage = chunk["usage"]
                    for choice in chunk.get("choices", []):
                        texts.append(choice.get("text") or "")
                        finish = choice.get("finish_reason") or finish
                self._m_requests.inc(route=route, status="200")
                return web.json_response({
                    "id": meta.get("id"), "object": "text_completion",
                    "created": meta.get("created"), "model": comp_req.model,
                    "choices": [{"index": 0, "text": "".join(texts),
                                 "finish_reason": finish, "logprobs": None}],
                    "usage": usage or usage_block(0, 0),
                })
            except ValueError as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "service_unavailable", 503)
            except OverloadedError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "overloaded", 503)
            except Exception as exc:  # noqa: BLE001
                log.exception("completion handler failed")
                self._m_requests.inc(route=route, status="500")
                return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _models(self, _request: web.Request) -> web.Response:
        return web.json_response({"object": "list",
                                  "data": self.manager.list_models()})

    async def _health(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "models": sorted(self.manager.models)})

    async def _live(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(body=self._runtime.metrics.expose(),
                            content_type="text/plain")
