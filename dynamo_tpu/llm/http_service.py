"""OpenAI-compatible HTTP service.

Capability parity with reference HttpService (lib/llm/src/http/service/
service_v2.rs:125-340, routers in openai.rs:1023-1094): ``/v1/chat/completions``,
``/v1/completions``, ``/v1/models``, ``/health``, ``/live``, ``/metrics`` with
SSE streaming, client-disconnect cancellation (disconnect.rs), request
validation errors in OpenAI error format, and per-route Prometheus metrics
including TTFT/ITL observations (http/service/metrics.rs).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time
from typing import AsyncIterator

from aiohttp import web
from pydantic import ValidationError

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.preprocessor import aggregate_chat_stream
from dynamo_tpu.llm.protocols import (
    ChatCompletionRequest,
    CompletionRequest,
    usage_block,
)
from dynamo_tpu.llm.recorder import finish_account, make_account
from dynamo_tpu.runtime import slo as slo_mod
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.errors import (AdapterNotFoundError,
                                       InvalidRequestError, NoInstancesError,
                                       OverloadedError, RateLimitedError)
from dynamo_tpu.runtime.logging import (current_trace, get_logger,
                                        parse_traceparent)
from dynamo_tpu.runtime.overload import (PRIORITY_BATCH, PRIORITY_INTERACTIVE,
                                         AdaptiveLimiter)
from dynamo_tpu.runtime.tracing import span

log = get_logger("http")

# Overload-defense request headers (docs/RESILIENCE.md "Overload model").
DEADLINE_HEADER = "x-request-deadline-ms"
PRIORITY_HEADER = "x-priority"
BROWNOUT_HEADER = "X-Overload-Brownout"
# Accounting: multi-tenant attribution for /debug/requests rollups.
TENANT_HEADER = "x-tenant"


def _response_object(full: dict, model: str, text: str | None) -> dict:
    """OpenAI Responses-API response object from an aggregated chat result."""
    usage = full.get("usage") or {}
    return {
        "id": f"resp-{full.get('id')}",
        "object": "response",
        "created_at": full.get("created"),
        "model": model,
        "status": "completed",
        "output": [{
            "type": "message", "role": "assistant",
            "content": [{"type": "output_text", "text": text or ""}],
        }],
        "output_text": text or "",
        "usage": {
            "input_tokens": usage.get("prompt_tokens", 0),
            "output_tokens": usage.get("completion_tokens", 0),
            "total_tokens": usage.get("total_tokens", 0),
        },
    }


def _adapter_of(served) -> str | None:
    """The LoRA adapter name a served model resolves to (None = base):
    register_adapter stamps the binding into the card's runtime extras."""
    extra = (served.entry.card.runtime_config.extra or {})
    return extra.get("adapter") if extra.get("lora_base") else None


def _error_body(message: str, err_type: str = "invalid_request_error",
                code: int = 400,
                retry_after_s: float | None = None) -> web.Response:
    headers = {}
    if retry_after_s is not None:
        # Retry-After is integer seconds (RFC 9110); round UP so "0.4s"
        # doesn't tell clients to hammer back immediately.
        headers["Retry-After"] = str(max(1, int(-(-retry_after_s // 1))))
    return web.Response(
        status=code,
        content_type="application/json",
        headers=headers,
        text=json.dumps({"error": {"message": message, "type": err_type,
                                   "param": None, "code": None}}))


class HttpService:
    def __init__(self, runtime, manager: ModelManager,
                 host: str = "0.0.0.0", port: int = 8000,
                 tls_cert_path: str | None = None,
                 tls_key_path: str | None = None,
                 overload: AdaptiveLimiter | None = None):
        self._runtime = runtime
        self.manager = manager
        self.host, self.port = host, port
        # TLS (reference frontend main.py --tls-cert-path/--tls-key-path):
        # both paths -> serve HTTPS; one without the other is a config
        # error surfaced at start().
        self.tls_cert_path = tls_cert_path
        self.tls_key_path = tls_key_path
        # Overload defense (runtime/overload.py): adaptive admission +
        # deadline-aware shedding + brownout around the generate routes.
        # None = no admission control (tests, embedded use).
        self.overload = overload
        # GET /debug/timeline provider: the frontend entrypoint installs
        # its TimelineCollector's merged fleet view before start(); left
        # None, the route serves this process's own journal.
        self.timeline_provider = None
        self._runner: web.AppRunner | None = None
        metrics = runtime.metrics.namespace("http")
        self._m_requests = metrics.counter(
            "http_requests_total", "HTTP requests", ["route", "status"])
        self._m_inflight = metrics.gauge(
            "http_inflight", "In-flight HTTP requests", ["route"])
        self._m_ttft = metrics.histogram(
            "ttft_seconds", "Time to first token", ["model"],
            buckets=[.01, .025, .05, .1, .25, .5, 1, 2.5, 5, 10])
        self._m_itl = metrics.histogram(
            "itl_seconds", "Inter-token latency", ["model"],
            buckets=[.001, .0025, .005, .01, .025, .05, .1, .25, 1])
        self._m_duration = metrics.histogram(
            "http_request_duration_seconds", "Request duration", ["route"])

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._completion)
        app.router.add_post("/v1/embeddings", self._embeddings)
        app.router.add_post("/v1/audio/transcriptions", self._transcriptions)
        app.router.add_post("/v1/responses", self._responses)
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/clear_kv_blocks", self._clear_kv_blocks)
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        # Fleet KV/capacity pane (llm/fleet.py): fans out over every
        # registered worker status server; typed partial results.
        app.router.add_get("/debug/fleet", self._debug_fleet)
        # Tracing/profiling debug API (runtime/health.py): in-process
        # pipelines get /debug/traces + /debug/profile on the frontend
        # port too, not only on the per-worker status server. The
        # frontend's /debug/kv serves the KV routers' fleet view +
        # decision telemetry.
        from dynamo_tpu.runtime.health import add_debug_routes
        add_debug_routes(app, kv_provider=self._kv_router_status,
                         perf_provider=self._perf_status,
                         timeline_provider=self.timeline_provider)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        ssl_ctx = None
        if self.tls_cert_path or self.tls_key_path:
            if not (self.tls_cert_path and self.tls_key_path):
                raise ValueError(
                    "TLS needs BOTH tls_cert_path and tls_key_path")
            import ssl
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.tls_cert_path, self.tls_key_path)
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=ssl_ctx)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("OpenAI %s service on %s:%d",
                 "HTTPS" if ssl_ctx else "HTTP", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- helpers --------------------------------------------------------------
    def _make_context(self, request: web.Request) -> Context:
        traceparent = request.headers.get("traceparent")
        trace = parse_traceparent(traceparent) if traceparent else None
        ctx = Context(trace_id=trace["trace_id"] if trace else None,
                      parent_span_id=trace["parent_id"] if trace else None)
        # Publish the request's trace context so every log line this
        # handler task emits carries trace_id/span_id (the formatters in
        # runtime/logging.py read this contextvar).
        current_trace.set({"trace_id": ctx.trace_id, "span_id": ctx.span_id})
        return ctx

    def _retry_after(self, exc: Exception | None = None) -> float:
        """Retry-After seconds for a shed/overloaded response: the
        error's own projection if it carries one, else the limiter's
        admission-queue projection, else the config default."""
        hint = getattr(exc, "retry_after_s", None)
        if hint:
            return hint
        if self.overload is not None:
            return self.overload.retry_after_s()
        ov = getattr(self._runtime.config, "overload", None)
        return ov.retry_after_default_s if ov is not None else 1.0

    def _overload_params(self, request: web.Request
                         ) -> tuple[str, float | None, web.Response | None]:
        """(priority, deadline_ms, error_response) from the overload
        request headers. A malformed deadline is the caller's bug: 400,
        not a silent default."""
        priority = request.headers.get(
            PRIORITY_HEADER, PRIORITY_INTERACTIVE).strip().lower()
        if priority not in (PRIORITY_INTERACTIVE, PRIORITY_BATCH):
            return PRIORITY_INTERACTIVE, None, _error_body(
                f"unknown {PRIORITY_HEADER} {priority!r} "
                f"(use 'interactive' or 'batch')")
        raw = request.headers.get(DEADLINE_HEADER)
        deadline_ms: float | None = None
        if raw is not None:
            try:
                deadline_ms = float(raw)
                if deadline_ms <= 0:
                    raise ValueError
            except ValueError:
                return priority, None, _error_body(
                    f"invalid {DEADLINE_HEADER} {raw!r} "
                    "(positive milliseconds)")
        return priority, deadline_ms, None

    async def _admit(self, request: web.Request, route: str, acct=None):
        """Run the overload-defense admission for one request. Returns
        (permit_ctx, response_headers, error_response): on a shed,
        error_response is the typed 429/503 (+ Retry-After) and the
        caller returns it immediately. ``acct`` (the accounting record)
        picks up tenant/priority/deadline, the admission queue wait, and
        — on a shed — the limiter's typed reason."""
        if acct is not None:
            acct["tenant"] = request.headers.get(TENANT_HEADER)
        null = contextlib.nullcontext()
        if self.overload is None:
            return null, {}, None
        priority, deadline_ms, bad = self._overload_params(request)
        if acct is not None:
            acct["priority"] = priority
            acct["deadline_ms"] = deadline_ms
        if bad is not None:
            self._m_requests.inc(route=route, status="400")
            if acct is not None:
                acct.update(status="error", reason="bad_overload_header",
                            http_status=400)
            return null, {}, bad
        t0 = time.monotonic()
        try:
            permit = await self.overload.admit(priority, deadline_ms)
        except RateLimitedError as exc:
            self._m_requests.inc(route=route, status="429")
            if acct is not None:
                acct.update(status="shed", http_status=429,
                            reason=getattr(exc, "shed_reason",
                                           "rate_limited"))
            return null, {}, _error_body(
                str(exc), "rate_limited", 429,
                retry_after_s=self._retry_after(exc))
        except OverloadedError as exc:
            self._m_requests.inc(route=route, status="503")
            if acct is not None:
                acct.update(status="shed", http_status=503,
                            reason=getattr(exc, "shed_reason", "overloaded"))
            return null, {}, _error_body(
                str(exc), "overloaded", 503,
                retry_after_s=self._retry_after(exc))
        if acct is not None:
            acct["queue_wait_s"] = time.monotonic() - t0
        headers = {}
        level = self.overload.pressure_level()
        if acct is not None:
            acct["brownout_level"] = level
        if level > 0:
            # Brownout reported in response metadata so clients can see
            # (and log) that they got degraded service.
            headers[BROWNOUT_HEADER] = str(level)
        return permit, headers, None

    def _apply_brownout(self, req) -> None:
        """Degradation hook: clamp max_tokens under brownout (the
        clamped value is visible in the response's usage block)."""
        if self.overload is None:
            return
        clamped = self.overload.clamp_max_tokens(
            getattr(req, "max_tokens", None))
        if clamped is not None:
            req.max_tokens = clamped

    async def _timed_first(self, chunks: AsyncIterator[dict], permit,
                           started: float, acct: dict | None = None
                           ) -> AsyncIterator[dict]:
        """Report time-to-first-chunk (the per-phase latency AIMD adapts
        against) into the admission permit — and, from the SAME timing
        point, feed the SLO plane's TTFT/ITL SLIs and the accounting
        record (TTFT, inter-chunk gaps, the usage block's token
        counts)."""
        plane = slo_mod.get_plane()
        last_t = None
        async for chunk in chunks:
            now = time.monotonic()
            if last_t is None:
                ttft = now - started
                if permit is not None and hasattr(permit, "note_latency"):
                    permit.note_latency(ttft)
                plane.observe_ttft(ttft)
                if acct is not None:
                    acct["ttft_s"] = ttft
            else:
                plane.observe_itl(now - last_t)
                if acct is not None:
                    acct["_itls"].append(now - last_t)
            last_t = now
            if acct is not None and isinstance(chunk, dict):
                usage = chunk.get("usage")
                if usage:
                    acct["prompt_tokens"] = usage.get("prompt_tokens")
                    acct["output_tokens"] = usage.get("completion_tokens")
            yield chunk

    def _account_done(self, acct: dict | None, ctx=None) -> None:
        """Finalize + ledger the accounting record exactly once. Any
        path that reached the route body lands here via its ``finally``
        — an unmarked record means the handler unwound without an
        explicit outcome (client disconnect / task cancellation).
        Availability/goodput SLIs are fed for real outcomes only (400s
        are the caller's bug, not an SLO event)."""
        if acct is None or "_t0" not in acct:
            return
        status = acct.get("status") or "cancelled"
        reason = acct.get("reason") or (
            "client_disconnect" if status == "cancelled" else None)
        http_status = acct.get("http_status")
        feed = status in ("ok", "shed") or (http_status or 0) >= 500
        finish_account(
            acct, status, reason, http_status, ctx=ctx,
            slo_plane=slo_mod.get_plane() if feed else None)

    async def _sse_stream(self, request: web.Request, chunks: AsyncIterator[dict],
                          ctx: Context, model: str,
                          extra_headers: dict | None = None
                          ) -> web.StreamResponse:
        # Pull the first chunk BEFORE sending headers so pipeline errors
        # (no instances, overload) still surface as proper HTTP statuses.
        start_t = time.monotonic()
        aiter = chunks.__aiter__()
        try:
            first_chunk = await aiter.__anext__()
        except StopAsyncIteration:
            first_chunk = None
        self._m_ttft.observe(time.monotonic() - start_t, model=model)
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     **(extra_headers or {})})
        await response.prepare(request)
        last_t = time.monotonic()
        try:
            if first_chunk is not None:
                await response.write(
                    b"data: " + json.dumps(first_chunk).encode() + b"\n\n")
            async for chunk in aiter:
                now = time.monotonic()
                self._m_itl.observe(now - last_t, model=model)
                last_t = now
                await response.write(
                    b"data: " + json.dumps(chunk).encode() + b"\n\n")
            await response.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: propagate kill so the worker frees the slot
            # (reference http/service/disconnect.rs).
            ctx.kill()
            raise
        return response

    # -- routes ---------------------------------------------------------------
    async def _chat(self, request: web.Request) -> web.StreamResponse:
        route = "chat_completions"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        acct = None
        ctx = None
        try:
            try:
                body = await request.json()
                chat_req = ChatCompletionRequest.model_validate(body)
            except (json.JSONDecodeError, ValidationError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(chat_req.model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {chat_req.model!r} not found",
                                   "model_not_found", 404)
            acct = make_account(route, chat_req.model)
            acct["adapter"] = _adapter_of(served)
            permit, meta_headers, shed = await self._admit(request, route,
                                                           acct)
            if shed is not None:
                return shed
            ctx = self._make_context(request)
            acct["request_id"], acct["trace_id"] = ctx.id, ctx.trace_id
            try:
                with permit, span("http.request", ctx=ctx, route=route,
                                  model=chat_req.model):
                    self._apply_brownout(chat_req)
                    chunks = self._timed_first(
                        served.preprocessor.generate(chat_req, ctx),
                        permit, time.monotonic(), acct)
                    if chat_req.stream:
                        resp = await self._sse_stream(request, chunks, ctx,
                                                      chat_req.model,
                                                      meta_headers)
                        self._m_requests.inc(route=route, status="200")
                        acct.update(status="ok", http_status=200)
                        return resp
                    # Non-streaming: force the usage chunk through the
                    # delta stream so the aggregate carries real token
                    # counts.
                    chat_req.stream_options = {"include_usage": True}
                    full = await aggregate_chat_stream(chunks, 0)
                    self._m_requests.inc(route=route, status="200")
                    acct.update(status="ok", http_status=200)
                    return web.json_response(full, headers=meta_headers)
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                acct.update(status="shed", reason="no_instances",
                            http_status=503)
                return _error_body(str(exc), "service_unavailable", 503,
                                   retry_after_s=self._retry_after(exc))
            except AdapterNotFoundError as exc:
                # The model name resolved to an adapter card whose base
                # worker does not hold the adapter: a naming error — 404
                # like an unknown model, typed so clients can tell which.
                self._m_requests.inc(route=route, status="404")
                acct.update(status="error", reason="adapter_not_found",
                            http_status=404)
                return _error_body(str(exc), "adapter_not_found", 404)
            except RateLimitedError as exc:
                self._m_requests.inc(route=route, status="429")
                acct.update(status="shed", http_status=429,
                            reason=getattr(exc, "shed_reason",
                                           "rate_limited"))
                return _error_body(str(exc), "rate_limited", 429,
                                   retry_after_s=self._retry_after(exc))
            except OverloadedError as exc:
                self._m_requests.inc(route=route, status="503")
                acct.update(status="shed", http_status=503,
                            reason=getattr(exc, "shed_reason", "overloaded"))
                return _error_body(str(exc), "overloaded", 503,
                                   retry_after_s=self._retry_after(exc))
            except (ValueError, InvalidRequestError) as exc:
                # Engine-level request validation (unsupported sampling
                # features, over-length prompts): the caller's fault —
                # whether raised in-process or typed over the wire.
                self._m_requests.inc(route=route, status="400")
                acct.update(status="error", reason="invalid_request",
                            http_status=400)
                return _error_body(str(exc))
            except Exception as exc:  # noqa: BLE001
                if isinstance(exc, ConnectionResetError):
                    acct.update(status="cancelled",
                                reason="client_disconnect")
                else:
                    acct.update(status="error", reason=type(exc).__name__,
                                http_status=500)
                log.exception("chat handler failed")
                self._m_requests.inc(route=route, status="500")
                return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._account_done(acct, ctx)
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _completion(self, request: web.Request) -> web.StreamResponse:
        route = "completions"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        acct = None
        ctx = None
        try:
            try:
                body = await request.json()
                comp_req = CompletionRequest.model_validate(body)
            except (json.JSONDecodeError, ValidationError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(comp_req.model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {comp_req.model!r} not found",
                                   "model_not_found", 404)
            acct = make_account(route, comp_req.model)
            acct["adapter"] = _adapter_of(served)
            permit, meta_headers, shed = await self._admit(request, route,
                                                           acct)
            if shed is not None:
                return shed
            ctx = self._make_context(request)
            acct["request_id"], acct["trace_id"] = ctx.id, ctx.trace_id
            try:
                with permit, span("http.request", ctx=ctx, route=route,
                                  model=comp_req.model):
                    self._apply_brownout(comp_req)
                    if not comp_req.stream:
                        # Force the usage chunk so the folded response
                        # has counts.
                        comp_req.stream_options = {"include_usage": True}
                    chunks = self._timed_first(
                        served.preprocessor.generate_completion(
                            comp_req, ctx),
                        permit, time.monotonic(), acct)
                    if comp_req.stream:
                        resp = await self._sse_stream(request, chunks, ctx,
                                                      comp_req.model,
                                                      meta_headers)
                        self._m_requests.inc(route=route, status="200")
                        acct.update(status="ok", http_status=200)
                        return resp
                    texts: list[str] = []
                    finish = None
                    meta: dict = {}
                    usage = None
                    async for chunk in chunks:
                        meta = {k: chunk.get(k, meta.get(k))
                                for k in ("id", "created")}
                        if chunk.get("usage"):
                            usage = chunk["usage"]
                        for choice in chunk.get("choices", []):
                            texts.append(choice.get("text") or "")
                            finish = choice.get("finish_reason") or finish
                    self._m_requests.inc(route=route, status="200")
                    acct.update(status="ok", http_status=200)
                    return web.json_response({
                        "id": meta.get("id"), "object": "text_completion",
                        "created": meta.get("created"),
                        "model": comp_req.model,
                        "choices": [{"index": 0, "text": "".join(texts),
                                     "finish_reason": finish,
                                     "logprobs": None}],
                        "usage": usage or usage_block(0, 0),
                    }, headers=meta_headers)
            except AdapterNotFoundError as exc:
                self._m_requests.inc(route=route, status="404")
                acct.update(status="error", reason="adapter_not_found",
                            http_status=404)
                return _error_body(str(exc), "adapter_not_found", 404)
            except ValueError as exc:
                self._m_requests.inc(route=route, status="400")
                acct.update(status="error", reason="invalid_request",
                            http_status=400)
                return _error_body(str(exc))
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                acct.update(status="shed", reason="no_instances",
                            http_status=503)
                return _error_body(str(exc), "service_unavailable", 503,
                                   retry_after_s=self._retry_after(exc))
            except RateLimitedError as exc:
                self._m_requests.inc(route=route, status="429")
                acct.update(status="shed", http_status=429,
                            reason=getattr(exc, "shed_reason",
                                           "rate_limited"))
                return _error_body(str(exc), "rate_limited", 429,
                                   retry_after_s=self._retry_after(exc))
            except OverloadedError as exc:
                self._m_requests.inc(route=route, status="503")
                acct.update(status="shed", http_status=503,
                            reason=getattr(exc, "shed_reason", "overloaded"))
                return _error_body(str(exc), "overloaded", 503,
                                   retry_after_s=self._retry_after(exc))
            except Exception as exc:  # noqa: BLE001
                if isinstance(exc, ConnectionResetError):
                    acct.update(status="cancelled",
                                reason="client_disconnect")
                else:
                    acct.update(status="error", reason=type(exc).__name__,
                                http_status=500)
                log.exception("completion handler failed")
                self._m_requests.inc(route=route, status="500")
                return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._account_done(acct, ctx)
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _embeddings(self, request: web.Request) -> web.Response:
        """OpenAI /v1/embeddings (reference openai.rs embeddings route):
        tokenizes the input(s) and asks an embedding-capable worker."""
        route = "embeddings"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        try:
            try:
                body = await request.json()
                model = body["model"]
                raw = body.get("input")
                if raw is None:
                    raise ValueError("missing 'input'")
            except (json.JSONDecodeError, KeyError, ValueError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {model!r} not found",
                                   "model_not_found", 404)
            inputs = raw if isinstance(raw, list) else [raw]
            if inputs and isinstance(inputs[0], int):
                inputs = [inputs]  # a single pre-tokenized prompt
            tokenizer = served.preprocessor.tokenizer
            token_lists = [t if isinstance(t, list) else tokenizer.encode(t)
                           for t in inputs]
            limit = served.entry.card.context_length
            if not token_lists or any(not t for t in token_lists):
                self._m_requests.inc(route=route, status="400")
                return _error_body("'input' must contain at least one "
                                   "non-empty prompt")
            if any(len(t) > limit for t in token_lists):
                self._m_requests.inc(route=route, status="400")
                return _error_body(
                    f"input exceeds the model context length ({limit})")
            try:
                if served.client is None:
                    # Static/local pipeline (unified launcher): reach the
                    # in-process engine behind Preprocessor -> Backend.
                    engine = served.preprocessor.inner.inner
                    vectors = await engine.embed(
                        token_lists, body.get("pooling", "last"))
                else:
                    stream = await served.client.round_robin(
                        {"embed": True, "token_lists": token_lists,
                         "pooling": body.get("pooling", "last")})
                    vectors = None
                    async for item in stream:
                        if "embeddings" in item:
                            vectors = item["embeddings"]
                    if vectors is None:
                        raise RuntimeError("worker returned no embeddings")
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "service_unavailable", 503,
                                   retry_after_s=self._retry_after(exc))
            self._m_requests.inc(route=route, status="200")
            total = sum(len(t) for t in token_lists)
            return web.json_response({
                "object": "list", "model": model,
                "data": [{"object": "embedding", "index": i, "embedding": v}
                         for i, v in enumerate(vectors)],
                "usage": {"prompt_tokens": total, "total_tokens": total},
            })
        except Exception as exc:  # noqa: BLE001
            log.exception("embeddings handler failed")
            self._m_requests.inc(route=route, status="500")
            return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _transcriptions(self, request: web.Request) -> web.Response:
        """OpenAI /v1/audio/transcriptions: WAV in (base64 ``file`` field;
        multipart upstreams decode before us), text out. The audio runs
        through the mel front end + audio encoder (llm/audio.py) and
        reaches the LLM as prompt-embedding spans (mm_embeds) — the
        reference's multimodal-processor contract
        (components/backends/trtllm multimodal), audio-first here."""
        route = "audio_transcriptions"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        try:
            import base64

            from dynamo_tpu.llm.audio import AudioEncoder, embed_audio
            from dynamo_tpu.llm.protocols import PreprocessedRequest
            try:
                body = await request.json()
                model = body["model"]
                wav = base64.b64decode(body["file"])
                max_tokens = int(body.get("max_tokens", 256))
                temperature = float(body.get("temperature", 0.0))
            except (json.JSONDecodeError, KeyError, ValueError,
                    TypeError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(f"need 'model' and base64 'file' "
                                   f"(+ numeric options): {exc}")
            served = self.manager.get(model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {model!r} not found",
                                   "model_not_found", 404)
            # The encoder projects to the LLM's hidden size, published in
            # the card's runtime extras (in-process engines expose it
            # directly).
            hidden = (served.entry.card.runtime_config.extra or {}) \
                .get("hidden_size")
            if hidden is None and served.client is None:
                hidden = served.preprocessor.inner.inner.runner.spec \
                    .hidden_size
            if hidden is None:
                self._m_requests.inc(route=route, status="400")
                return _error_body(
                    f"model {model!r} did not publish hidden_size; "
                    "audio input needs an embedding-capable worker")
            cache = getattr(self, "_audio_encoders", None)
            if cache is None:
                cache = self._audio_encoders = {}
            encoder = cache.get((model, hidden))
            if encoder is None:
                # Trained weights: card runtime extras or env override
                # (scripts/convert_whisper_encoder.py produces the
                # checkpoint). Without them the encoder is DETERMINISTIC
                # RANDOM INIT — the route works end to end but emits
                # model babble, flagged in the response.
                import os as _os
                weights = (_os.environ.get("DTPU_AUDIO_ENCODER_WEIGHTS")
                           or (served.entry.card.runtime_config.extra
                               or {}).get("audio_encoder_weights"))
                encoder = cache[(model, hidden)] = AudioEncoder(
                    hidden, weights_path=weights)
            span, n_audio = embed_audio(wav, encoder)
            tokenizer = served.preprocessor.tokenizer
            prompt_tokens = tokenizer.encode(
                body.get("prompt") or "Transcribe the audio.")
            req = PreprocessedRequest(
                model=model, token_ids=[0] * n_audio + prompt_tokens,
                mm_embeds=[span])
            req.stop_conditions.max_tokens = max_tokens
            req.sampling_options.temperature = temperature
            req.eos_token_ids = tokenizer.eos_token_ids()
            ctx = self._make_context(request)
            toks: list[int] = []
            try:
                if served.client is None:
                    engine = served.preprocessor.inner.inner
                    stream = engine.generate(req, ctx)
                else:
                    stream = await served.client.round_robin(
                        req.to_wire(), context=ctx)
                async for out in stream:
                    toks.extend(out.get("token_ids", []))
                    if out.get("finish_reason"):
                        break
            except NoInstancesError as exc:
                self._m_requests.inc(route=route, status="503")
                return _error_body(str(exc), "service_unavailable", 503,
                                   retry_after_s=self._retry_after(exc))
            self._m_requests.inc(route=route, status="200")
            resp = {
                "text": tokenizer.decode(toks),
                "usage": {"input_tokens": len(req.token_ids),
                          "output_tokens": len(toks),
                          "audio_tokens": n_audio},
            }
            if getattr(encoder, "untrained", False):
                resp["warnings"] = [
                    "audio encoder is random-init (no "
                    "audio_encoder_weights configured): output is not a "
                    "real transcription"]
            return web.json_response(resp)
        except Exception as exc:  # noqa: BLE001
            log.exception("transcriptions handler failed")
            self._m_requests.inc(route=route, status="500")
            return _error_body(f"internal error: {exc}", "internal_error",
                               500)
        finally:
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _responses(self, request: web.Request) -> web.Response:
        """OpenAI /v1/responses (reference openai.rs:1023-1094 responses
        route): adapts the Responses API onto the chat pipeline
        (non-streaming)."""
        route = "responses"
        started = time.monotonic()
        self._m_inflight.inc(route=route)
        acct = None
        ctx = None
        try:
            try:
                body = await request.json()
                model = body["model"]
                raw_input = body.get("input", "")
            except (json.JSONDecodeError, KeyError) as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            served = self.manager.get(model)
            if served is None:
                self._m_requests.inc(route=route, status="404")
                return _error_body(f"model {model!r} not found",
                                   "model_not_found", 404)
            if isinstance(raw_input, str):
                messages = [{"role": "user", "content": raw_input}]
            else:
                messages = [{"role": m.get("role", "user"),
                             "content": m.get("content", "")}
                            for m in raw_input]
            if body.get("instructions"):
                messages.insert(0, {"role": "system",
                                    "content": body["instructions"]})
            try:
                chat_req = ChatCompletionRequest(
                    model=model, messages=messages,
                    max_tokens=body.get("max_output_tokens"),
                    temperature=body.get("temperature"),
                    top_p=body.get("top_p"),
                    stream_options={"include_usage": True})
            except ValidationError as exc:
                self._m_requests.inc(route=route, status="400")
                return _error_body(str(exc))
            acct = make_account(route, model)
            permit, meta_headers, shed = await self._admit(request, route,
                                                           acct)
            if shed is not None:
                return shed
            ctx = self._make_context(request)
            acct["request_id"], acct["trace_id"] = ctx.id, ctx.trace_id
            with permit, span("http.request", ctx=ctx, route=route,
                              model=model):
                self._apply_brownout(chat_req)
                chunks = self._timed_first(
                    served.preprocessor.generate(chat_req, ctx),
                    permit, time.monotonic(), acct)
                if body.get("stream"):
                    resp = await self._responses_sse(request, chunks, ctx,
                                                     model)
                    self._m_requests.inc(route=route, status="200")
                    acct.update(status="ok", http_status=200)
                    return resp
                full = await aggregate_chat_stream(chunks, 0)
                msg = full["choices"][0]["message"]
                usage = full.get("usage") or {}
                self._m_requests.inc(route=route, status="200")
                acct.update(status="ok", http_status=200)
                return web.json_response(
                    _response_object(full, model, msg.get("content")),
                    headers=meta_headers)
        except RateLimitedError as exc:
            self._m_requests.inc(route=route, status="429")
            if acct is not None:
                acct.update(status="shed", http_status=429,
                            reason=getattr(exc, "shed_reason",
                                           "rate_limited"))
            return _error_body(str(exc), "rate_limited", 429,
                               retry_after_s=self._retry_after(exc))
        except OverloadedError as exc:
            self._m_requests.inc(route=route, status="503")
            if acct is not None:
                acct.update(status="shed", http_status=503,
                            reason=getattr(exc, "shed_reason", "overloaded"))
            return _error_body(str(exc), "overloaded", 503,
                               retry_after_s=self._retry_after(exc))
        except NoInstancesError as exc:
            self._m_requests.inc(route=route, status="503")
            if acct is not None:
                acct.update(status="shed", reason="no_instances",
                            http_status=503)
            return _error_body(str(exc), "service_unavailable", 503,
                               retry_after_s=self._retry_after(exc))
        except AdapterNotFoundError as exc:
            self._m_requests.inc(route=route, status="404")
            if acct is not None:
                acct.update(status="error", reason="adapter_not_found",
                            http_status=404)
            return _error_body(str(exc), "adapter_not_found", 404)
        except Exception as exc:  # noqa: BLE001
            if acct is not None:
                if isinstance(exc, ConnectionResetError):
                    acct.update(status="cancelled",
                                reason="client_disconnect")
                else:
                    acct.update(status="error", reason=type(exc).__name__,
                                http_status=500)
            log.exception("responses handler failed")
            self._m_requests.inc(route=route, status="500")
            return _error_body(f"internal error: {exc}", "internal_error", 500)
        finally:
            self._account_done(acct, ctx)
            self._m_inflight.dec(route=route)
            self._m_duration.observe(time.monotonic() - started, route=route)

    async def _responses_sse(self, request: web.Request, chunks,
                             ctx: Context, model: str) -> web.StreamResponse:
        """Responses-API streaming: response.output_text.delta events per
        content delta, then response.completed with the final object."""
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await response.prepare(request)

        async def send(event: str, data: dict) -> None:
            await response.write(
                f"event: {event}\ndata: {json.dumps(data)}\n\n".encode())

        content: list[str] = []
        meta: dict = {}
        usage: dict = {}
        try:
            async for chunk in chunks:
                meta = {k: chunk.get(k, meta.get(k))
                        for k in ("id", "created")}
                if chunk.get("usage"):
                    usage = chunk["usage"]
                for choice in chunk.get("choices", []):
                    piece = choice.get("delta", {}).get("content")
                    if piece:
                        content.append(piece)
                        await send("response.output_text.delta",
                                   {"delta": piece})
            full = {"id": meta.get("id"), "created": meta.get("created"),
                    "usage": usage}
            await send("response.completed",
                       {"response": _response_object(full, model,
                                                     "".join(content))})
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()
            raise
        return response

    async def _clear_kv_blocks(self, _request: web.Request) -> web.Response:
        """Admin route (reference openai.rs clear_kv_blocks): tell every
        worker instance of every served model to drop its reusable prefix
        cache."""
        results: dict[str, dict] = {}
        for name, served in self.manager.models.items():
            per_model: dict[str, int] = {}
            if served.client is None:
                engine = served.preprocessor.inner.inner
                clear = getattr(engine, "clear_kv_blocks", None)
                if clear is not None:
                    per_model["local"] = await clear()
            else:
                for iid in served.client.instance_ids():
                    try:
                        stream = await served.client.direct(
                            {"clear_kv_blocks": True}, iid)
                        async for item in stream:
                            if "cleared" in item:
                                per_model[f"{iid:x}"] = item["cleared"]
                    except Exception as exc:  # noqa: BLE001 — report per-worker
                        per_model[f"{iid:x}"] = -1
                        log.warning("clear_kv_blocks failed on %x: %s",
                                    iid, exc)
            results[name] = per_model
        return web.json_response({"cleared": results})

    # -- KV & capacity pane (docs/OBSERVABILITY.md "KV & capacity") -----------
    def _kv_router_status(self) -> dict:
        """This frontend's /debug/kv: per-model KV-router fleet view +
        decision telemetry, plus in-process engines' KV state for the
        unified launcher (no worker status server to ask)."""
        routers = {}
        engines = {}
        for name, served in self.manager.models.items():
            status = getattr(served.router, "kv_status", None)
            if status is not None:
                routers[name] = status()
            if served.client is None:
                engine = getattr(
                    getattr(served.preprocessor, "inner", None), "inner",
                    None)
                engine_status = getattr(engine, "kv_status", None)
                if engine_status is not None:
                    engines[name] = engine_status()
        return {"role": "frontend", "routers": routers, "engines": engines}

    def _perf_status(self) -> dict:
        """This frontend's /debug/perf: the process-global compile
        observatory plus in-process engines' full perf view (unified
        launcher — no worker status server to ask)."""
        from dynamo_tpu.engine.perf import process_perf_status
        engines = {}
        for name, served in self.manager.models.items():
            if served.client is not None:
                continue
            engine = getattr(
                getattr(served.preprocessor, "inner", None), "inner", None)
            status = getattr(engine, "perf_status", None)
            if status is not None:
                engines[name] = status()
        body = process_perf_status()
        body["role"] = "frontend"
        body["engines"] = engines
        return body

    async def _debug_fleet(self, request: web.Request) -> web.Response:
        """GET /debug/fleet: merged per-worker KV/capacity view from
        every registered worker status server (bounded fan-out, typed
        partial results — one down worker never breaks the pane)."""
        from dynamo_tpu.llm.fleet import (DEFAULT_CONCURRENCY,
                                          DEFAULT_TIMEOUT_S,
                                          fleet_kv_snapshot)
        if not self._runtime.has_discovery:
            return web.json_response(
                {"error": "static runtime: no discovery plane to "
                 "enumerate worker status servers"}, status=503)
        try:
            timeout_s = float(request.query.get("timeout_s",
                                                DEFAULT_TIMEOUT_S))
            concurrency = int(request.query.get("concurrency",
                                                DEFAULT_CONCURRENCY))
        except ValueError:
            return _error_body("timeout_s/concurrency must be numeric")
        snapshot = await fleet_kv_snapshot(
            self._runtime, timeout_s=timeout_s, concurrency=concurrency,
            router_view=self._kv_router_status)
        return web.json_response(snapshot)

    async def _models(self, _request: web.Request) -> web.Response:
        return web.json_response({"object": "list",
                                  "data": self.manager.list_models()})

    async def _health(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "models": sorted(self.manager.models)})

    async def _live(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(body=self._runtime.metrics.expose(),
                            content_type="text/plain")
