"""Request-migration operator for fault tolerance.

Capability parity with reference Migration (lib/llm/src/migration.rs:26-120
RetryManager): when a worker dies mid-stream (StreamIncompleteError from the
request plane), re-issue the request to another instance with the
already-generated tokens appended to the prompt, up to ``migration_limit``
retries. Workers signal incompleteness via connection loss or an explicit
incomplete-stream error (docs/guides/backend.md §Migrate).
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine, Operator
from dynamo_tpu.runtime.errors import StreamIncompleteError
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("migration")


class Migration(Operator):
    def __init__(self, migration_limit: int = 0, inner: AsyncEngine | None = None):
        super().__init__(inner)
        self.migration_limit = migration_limit

    async def generate(self, request: PreprocessedRequest | dict,
                       context: Context) -> AsyncIterator[LLMEngineOutput]:
        assert self.inner is not None
        original = (request if isinstance(request, PreprocessedRequest)
                    else PreprocessedRequest.from_wire(request))
        retries_left = self.migration_limit
        accumulated: list[int] = []
        req = original
        while True:
            try:
                async for raw in self.inner.generate(req.to_wire(), context):
                    out = (raw if isinstance(raw, LLMEngineOutput)
                           else LLMEngineOutput.from_wire(raw))
                    accumulated.extend(out.token_ids)
                    yield out
                return
            except StreamIncompleteError as exc:
                if retries_left <= 0 or context.is_stopped:
                    raise
                retries_left -= 1
                log.warning(
                    "Stream disconnected (%s)... recreating stream "
                    "(%d retries left, carrying %d generated tokens)",
                    exc, retries_left, len(accumulated))
                # Continue generation on another worker: the ORIGINAL prompt
                # plus everything generated so far becomes the new prompt; the
                # budget shrinks by total emitted. Rebuilding from `original`
                # each retry keeps repeated migrations from double-counting.
                new_req = original.model_copy(deep=True)
                new_req.token_ids = original.token_ids + accumulated
                if new_req.stop_conditions.max_tokens is not None:
                    new_req.stop_conditions.max_tokens = max(
                        1, new_req.stop_conditions.max_tokens - len(accumulated))
                req = new_req
