"""Request-migration operator for fault tolerance.

Capability parity with reference Migration (lib/llm/src/migration.rs:26-120
RetryManager): when a worker dies mid-stream (StreamIncompleteError from the
request plane), re-issue the request to another instance with the
already-generated tokens appended to the prompt, up to ``migration_limit``
retries. Workers signal incompleteness via connection loss or an explicit
incomplete-stream error (docs/guides/backend.md §Migrate).

Observability: each retry bumps the ``migrations_total`` counter (when a
metrics registry is supplied) and records a ``migration.retry`` span on
the request's trace, so migrated requests show up in /debug/traces and
/metrics instead of only a log line. Retries pace themselves through
``policies.MIGRATION`` with a shared per-operator retry budget: when a
worker death strands many streams at once, their redials jitter and
spread instead of storming the survivors in lockstep.
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime import chaos, journal
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine, Operator
from dynamo_tpu.runtime.errors import StreamIncompleteError
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, RetryBudget, policies
from dynamo_tpu.runtime.tracing import span

log = get_logger("migration")


class Migration(Operator):
    def __init__(self, migration_limit: int = 0,
                 inner: AsyncEngine | None = None, metrics=None):
        super().__init__(inner)
        self.migration_limit = migration_limit
        # Shared across every stream this operator serves: a mass
        # disconnect (one worker death strands its whole batch) drains
        # the bucket and later migrations back off at the policy max.
        self._budget = RetryBudget(rate=20.0, burst=50.0)
        self._m_migrations = None
        if metrics is not None:
            self._m_migrations = metrics.counter(
                "migrations_total",
                "Mid-stream migrations (retries after disconnect)")

    async def generate(self, request: PreprocessedRequest | dict,
                       context: Context) -> AsyncIterator[LLMEngineOutput]:
        assert self.inner is not None
        original = (request if isinstance(request, PreprocessedRequest)
                    else PreprocessedRequest.from_wire(request))
        retries_left = self.migration_limit
        accumulated: list[int] = []
        req = original
        attempt = 0
        backoff = Backoff(policies.MIGRATION, budget=self._budget)
        while True:
            try:
                async for raw in self.inner.generate(req.to_wire(), context):
                    out = (raw if isinstance(raw, LLMEngineOutput)
                           else LLMEngineOutput.from_wire(raw))
                    accumulated.extend(out.token_ids)
                    yield out
                return
            except StreamIncompleteError as exc:
                budget = original.stop_conditions.max_tokens
                if budget is not None and len(accumulated) >= budget:
                    # The stream died on the final boundary: everything
                    # the caller asked for was already delivered. A
                    # retry with the max(1, ...) floor would overshoot
                    # the budget by a token — treat as complete instead.
                    return
                if retries_left <= 0 or context.is_stopped:
                    raise
                retries_left -= 1
                attempt += 1
                # Per-request attribution: the accounting record
                # (llm/recorder.py) reads this off the frontend-side ctx.
                context.values["migrations"] = attempt
                # The worker may have declared WHY the stream ended (a
                # role-flip drain sends "incomplete:role_flip"): a typed
                # reason beats the generic disconnect, and the strongest
                # reason seen wins across repeated migrations so a
                # follow-up plain disconnect can't erase the attribution.
                if exc.reason or "migration_reason" not in context.values:
                    context.values["migration_reason"] = (exc.reason
                                                          or "disconnect")
                if self._m_migrations is not None:
                    self._m_migrations.inc()
                # Decision plane: the migration decision with its typed
                # reason. Cause: the worker's drain/flip when the typed
                # reason says so (the flip events arrive on the merged
                # timeline from the worker's own journal), else a chaos
                # injection when one is active.
                journal.emit(
                    EventKind.MIGRATION,
                    cause=(journal.recent_ref(EventKind.CHAOS_INJECT)
                           if chaos.ACTIVE else None),
                    trace_id=context.trace_id, attempt=attempt,
                    reason=context.values.get("migration_reason"),
                    carried_tokens=len(accumulated),
                    retries_left=retries_left,
                    worker_id=context.values.get("worker_id"))
                log.warning(
                    "Stream disconnected (%s)... recreating stream "
                    "(%d retries left, carrying %d generated tokens)",
                    exc, retries_left, len(accumulated))
                # The span covers the backoff pause and joins the
                # request's trace (frontend http.request -> ... ->
                # migration.retry), making migrated requests visible in
                # /debug/traces.
                with span("migration.retry", ctx=context, attempt=attempt,
                          carried_tokens=len(accumulated),
                          retries_left=retries_left, reason=str(exc)):
                    await backoff.sleep()
                # Continue generation on another worker: the ORIGINAL prompt
                # plus everything generated so far becomes the new prompt; the
                # budget shrinks by total emitted. Rebuilding from `original`
                # each retry keeps repeated migrations from double-counting.
                new_req = original.model_copy(deep=True)
                new_req.token_ids = original.token_ids + accumulated
                if new_req.stop_conditions.max_tokens is not None:
                    new_req.stop_conditions.max_tokens = max(
                        1, new_req.stop_conditions.max_tokens - len(accumulated))
                req = new_req
