"""Model deployment cards and registration.

Capability parity with reference ModelDeploymentCard / ModelEntry
(lib/llm/src/model_card.rs:91-236, discovery MODEL_ROOT_PATH): the card carries
everything a frontend needs to serve a model — tokenizer artifact (shipped via
the coordinator object store, model_card.rs:245-351), chat template, context
length, kv block size, migration limit, runtime config — and the entry maps the
model name to the worker endpoint that serves it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any

from dynamo_tpu.llm.tokenizer import Tokenizer

MODEL_ROOT = "models/"

# Default chat template used when a model ships none: a minimal ChatML-style
# template (reference ships model-specific templates via the card).
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|im_start|>{{ message['role'] }}\n{{ message['content'] }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)


@dataclasses.dataclass
class ModelRuntimeConfig:
    """Engine capacity facts published at registration (reference
    ModelRuntimeConfig, local_model.rs — total_kv_blocks, max_num_seqs...)."""

    total_kv_blocks: int | None = None
    max_num_seqs: int | None = None
    max_num_batched_tokens: int | None = None
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict | None) -> "ModelRuntimeConfig":
        data = data or {}
        return cls(**{f.name: data.get(f.name) if f.name != "extra"
                      else data.get("extra", {}) for f in dataclasses.fields(cls)})


@dataclasses.dataclass
class ModelDeploymentCard:
    name: str
    model_type: str = "chat"  # chat | completions | embedding
    tokenizer_key: str | None = None  # object-store key for tokenizer.json bytes
    chat_template: str | None = None
    context_length: int = 8192
    kv_cache_block_size: int = 16  # reference default (docs/guides/backend.md)
    migration_limit: int = 0
    # Backward-edge parsers (reference lib/parsers): names resolved by
    # llm/parsers.py TOOL_FORMATS / REASONING_FORMATS; None = raw text.
    tool_call_parser: str | None = None
    reasoning_parser: str | None = None
    runtime_config: ModelRuntimeConfig = dataclasses.field(
        default_factory=ModelRuntimeConfig)

    def to_wire(self) -> dict:
        d = dataclasses.asdict(self)
        d["runtime_config"] = self.runtime_config.to_wire()
        return d

    @classmethod
    def from_wire(cls, data: dict) -> "ModelDeploymentCard":
        data = dict(data)
        data["runtime_config"] = ModelRuntimeConfig.from_wire(
            data.get("runtime_config"))
        return cls(**{f.name: data[f.name] for f in dataclasses.fields(cls)
                      if f.name in data})


@dataclasses.dataclass
class ModelEntry:
    """models/{slug} KV entry (reference discovery/ModelEntry)."""

    model_name: str
    namespace: str
    component: str
    endpoint: str
    model_type: str
    card: ModelDeploymentCard

    def to_wire(self) -> dict:
        d = dataclasses.asdict(self)
        d["card"] = self.card.to_wire()
        return d

    @classmethod
    def from_wire(cls, data: dict) -> "ModelEntry":
        return cls(model_name=data["model_name"], namespace=data["namespace"],
                   component=data["component"], endpoint=data["endpoint"],
                   model_type=data.get("model_type", "chat"),
                   card=ModelDeploymentCard.from_wire(data["card"]))


def model_slug(name: str) -> str:
    return name.replace("/", "--")


async def register_llm(
    runtime,
    endpoint,
    model_name: str,
    tokenizer: Tokenizer,
    model_type: str = "chat",
    chat_template: str | None = None,
    context_length: int = 8192,
    kv_cache_block_size: int = 16,
    migration_limit: int = 0,
    tool_call_parser: str | None = None,
    reasoning_parser: str | None = None,
    runtime_config: ModelRuntimeConfig | None = None,
) -> ModelEntry:
    """Register a served model: ship the tokenizer to the object store and put
    the ModelEntry under models/ on the worker's primary lease (reference
    register_llm, bindings rust/lib.rs:143 -> model_card.rs:374).
    """
    client = runtime.require_coordinator()
    blob = tokenizer.to_bytes()
    tok_key = f"tokenizers/{model_slug(model_name)}-{hashlib.sha256(blob).hexdigest()[:12]}"
    await client.object_put(tok_key, blob)
    card = ModelDeploymentCard(
        name=model_name, model_type=model_type, tokenizer_key=tok_key,
        chat_template=chat_template, context_length=context_length,
        kv_cache_block_size=kv_cache_block_size, migration_limit=migration_limit,
        tool_call_parser=tool_call_parser, reasoning_parser=reasoning_parser,
        runtime_config=runtime_config or ModelRuntimeConfig())
    entry = ModelEntry(model_name=model_name,
                       namespace=endpoint.component.namespace,
                       component=endpoint.component.name,
                       endpoint=endpoint.name, model_type=model_type, card=card)
    # Keyed per-instance so N workers of one model coexist; the frontend
    # dedups by model name (reference keys entries by lease id too).
    key = f"{MODEL_ROOT}{model_slug(model_name)}/{runtime.instance_id:x}"
    await client.kv_put(key, entry.to_wire(), use_primary_lease=True)

    # The card rides the primary lease: if the lease expires (e.g. the
    # process stalls past the TTL during engine compilation) the coordinator
    # deletes it — re-put on re-grant so the model doesn't silently vanish
    # from discovery (the endpoint instance re-registers the same way,
    # runtime/service.py). The _active guard lets deregister_llm retire
    # the replay: a worker that role-flipped away from decode must not
    # resurrect its model card on the next lease regrant.
    _active_cards.add(key)

    async def _reput(_new_lease_id: int) -> None:
        if key in _active_cards:
            await client.kv_put(key, entry.to_wire(), use_primary_lease=True)

    client.on_lease_recreated(_reput)
    return entry


async def register_adapter(
    runtime,
    endpoint,
    adapter_name: str,
    base_name: str,
    tokenizer: Tokenizer,
    runtime_config: ModelRuntimeConfig | None = None,
    **kwargs,
) -> ModelEntry:
    """Register a LoRA adapter as a SERVED MODEL NAME: a full model card
    under ``models/{adapter-slug}`` pointing at the BASE model's worker
    endpoint, with ``runtime_config.extra`` carrying the adapter/base
    binding. The frontend resolves the OpenAI ``model`` field to this
    card like any other model; its preprocessor then stamps the wire
    request with ``adapter=<name>`` so the worker forwards it through
    the right LoRA slot (engine/lora.py). Adapters are cheap to
    replicate — every worker of the base model can serve the name, so
    the entry is per-instance exactly like base registrations."""
    rc = runtime_config or ModelRuntimeConfig()
    rc.extra = dict(rc.extra or {})
    rc.extra.update({"lora_base": base_name, "adapter": adapter_name})
    return await register_llm(runtime, endpoint, adapter_name, tokenizer,
                              runtime_config=rc, **kwargs)


#: Model-card keys this process still serves; deregister_llm removes a
#: key so lease-recreated replays stop re-putting it.
_active_cards: set = set()


async def deregister_llm(runtime, model_name: str) -> None:
    """Remove this worker's model-card registration (role flips away from
    decode/agg: the frontend must drop this instance from the model's
    set instead of routing into a prefill-only worker)."""
    key = f"{MODEL_ROOT}{model_slug(model_name)}/{runtime.instance_id:x}"
    _active_cards.discard(key)
    try:
        await runtime.require_coordinator().kv_delete(key)
    except (ConnectionError, OSError, RuntimeError):
        # Coordinator down: the key rides our lease and the replay guard
        # above is already cleared, so it cannot come back.
        pass


async def fetch_tokenizer(client, card: ModelDeploymentCard) -> Tokenizer:
    if card.tokenizer_key is None:
        raise ValueError(f"model card {card.name} has no tokenizer artifact")
    blob = await client.object_get(card.tokenizer_key)
    if blob is None:
        raise KeyError(f"tokenizer object {card.tokenizer_key} missing")
    return Tokenizer.from_bytes(blob)
