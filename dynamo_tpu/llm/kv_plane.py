"""Direct worker<->worker KV data plane — the NIXL role, TPU-first.

The reference moves KV blocks GPU<->GPU/host/disk over NIXL RDMA with a
layout/metadata handshake (lib/llm/src/block_manager/storage/nixl.rs,
block_manager/layout/nixl.rs, docs/architecture/dynamo_flow.md §NIXL).
This module is the TPU-native equivalent: a dedicated bulk-transfer plane
between workers that keeps KV bytes OFF the coordinator-discovered
request plane. Paths, fastest first, negotiated per transfer by a
metadata ticket (the role of NIXL's metadata exchange through etcd):

1. ``jax``  — ``jax.experimental.transfer``: device-to-device pull over
   ICI/DCN with no host staging. Probed at import-site: the probe
   actually stages and pulls a loopback array, because several PJRT
   builds (CPU, tunneled TPU) advertise the module but raise
   UNIMPLEMENTED on ``PJRT_Client_CreateBuffersForAsyncHostToDevice``.
   Activates on real TPU pods; falls through cleanly elsewhere.
2. ``socket`` — a direct TCP bulk plane: the source worker serves its
   extracted KV (host-staged via the runner's async D2H copy, which
   overlaps decode windows) on its OWN listening socket; the sink pulls
   with ``recv_into`` a preallocated buffer. One NIC hop, no msgpack
   re-framing of multi-MB payloads, no coordinator in the data path.
3. Inline parcel chunks on the request plane (llm/kv_transfer.py) — the
   v0 fallback, still emitted when the prefill worker has no plane.

The ticket contract: ``{"id", "addr", "jax_addr"?, "shape", "dtype",
"nbytes", "prompt_len"}`` rides the ordinary (small) response stream;
only the bulk bytes take the direct path.

The same socket also serves ``blocks`` requests — peer workers fetch KV
blocks from this worker's G2/G3 host tiers by block hash (the G4
remote-tier role, block_manager.rs:76-82 CacheLevel G1..G4), enabling
cross-worker prefix reuse without recompute.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
import time
from typing import Callable

import msgpack
import numpy as np

from dynamo_tpu.runtime import chaos
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, policies

log = get_logger("kv_plane")

_LEN = struct.Struct(">I")
_MAX_CTRL = 64 * 1024 * 1024  # control frames stay small; bulk is raw
_SEND_CHUNK = 4 << 20

STAGED_TTL_S = 120.0  # unseen tickets expire (sink crashed mid-handshake)


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def dtype_of(name: str) -> np.dtype:
    return np.dtype(_bf16() if name == "bfloat16" else name)


# -- sync frame helpers (server thread + client executor threads) -------------

def _send_ctrl(sock: socket.socket, obj: dict) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return bytes(buf)


def _recv_ctrl(sock: socket.socket) -> dict:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > _MAX_CTRL:
        raise ValueError(f"control frame too large: {length}")
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


def _send_bulk(sock: socket.socket, arr: np.ndarray) -> None:
    # uint8 view first: bfloat16 has no buffer-protocol format char, and
    # the view + memoryview is zero-copy from the numpy buffer either way.
    data = memoryview(np.ascontiguousarray(arr).view(np.uint8).reshape(-1))
    for off in range(0, len(data), _SEND_CHUNK):
        sock.sendall(data[off:off + _SEND_CHUNK])


def _recv_bulk_into(sock: socket.socket, buf: memoryview,
                    deadline: float | None = None) -> None:
    """Fill ``buf`` from the socket. ``deadline`` (time.monotonic value)
    bounds the WHOLE payload, not just each recv: per-recv timeouts
    reset on every arriving segment, so a trickling peer could stretch a
    multi-MB transfer arbitrarily while never tripping them (the G4
    consult's engine-thread budget must be a hard wall clock)."""
    got = 0
    n = len(buf)
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    f"bulk recv deadline exceeded ({got}/{n} bytes)")
            sock.settimeout(remaining)
        r = sock.recv_into(buf[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-payload")
        got += r


# -- jax.experimental.transfer probe ------------------------------------------

_jax_probe: bool | None = None
_jax_server = None


def jax_transfer_usable() -> bool:
    """True iff the device-to-device transfer engine actually works on
    this backend (loopback stage+pull; cached). CPU and tunneled-TPU
    PJRT builds raise UNIMPLEMENTED from the buffer-import hook, so a
    hasattr check is not enough."""
    global _jax_probe
    if _jax_probe is not None:
        return _jax_probe
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import transfer
        from jax.sharding import SingleDeviceSharding

        dev = jax.local_devices()[0]
        srv = transfer.start_transfer_server(dev.client)
        arr = jnp.arange(8, dtype=jnp.float32)
        # dtpu: ignore[blocking-call-in-async] -- one-shot 8-float capability probe at server construction
        arr.block_until_ready()
        srv.await_pull(0, [arr])
        conn = srv.connect(srv.address())
        out = conn.pull(0, [jax.ShapeDtypeStruct(
            arr.shape, arr.dtype, sharding=SingleDeviceSharding(dev))])
        np.asarray(out[0])
        _jax_probe = True
    except Exception as exc:  # noqa: BLE001 — any failure means "no"
        log.info("jax.experimental.transfer unusable on this backend "
                 "(%s: %s); KV plane uses the socket path",
                 type(exc).__name__, exc)
        _jax_probe = False
    return _jax_probe


def _get_jax_server():
    """Process-wide transfer server (lazy; only when the probe passed)."""
    global _jax_server
    if _jax_server is None:
        import jax
        from jax.experimental import transfer

        _jax_server = transfer.start_transfer_server(
            jax.local_devices()[0].client)
    return _jax_server


class _Staged:
    __slots__ = ("meta", "payload", "resolve", "t", "jax_uuid", "groups",
                 "in_progress")

    def __init__(self, meta: dict, payload, resolve, jax_uuid,
                 groups=None):
        self.meta = meta
        self.payload = payload      # np.ndarray once resolved
        self.resolve = resolve      # () -> np.ndarray, or None
        self.t = time.monotonic()
        self.jax_uuid = jax_uuid
        # Claimed by a pull connection (under the server lock): a second
        # concurrent pull of the same ticket must not also transmit —
        # double-serving runs grouped resolvers twice concurrently and
        # double-counts transfer metrics. Cleared if the send fails, so
        # the sink's retry still finds the parcel staged.
        self.in_progress = False
        # Pipelined socket path: [(n_pages, () -> np.ndarray), ...] —
        # page-group resolvers whose D2H copies were dispatched together
        # at extract time, so sending group i overlaps group i+1's copy
        # (the extract leg is ~97% of the transfer tax on a tunneled
        # chip; reference offload.rs MAX_CONCURRENT_TRANSFERS overlap).
        self.groups = groups

    def array(self) -> np.ndarray:
        if self.payload is None:
            self.payload = self.resolve()
            self.resolve = None
        return self.payload


class KvPlaneServer:
    """Source side: stages KV parcels for direct pull and serves host-tier
    blocks to peers. One per worker process; thread-based (bulk socket
    I/O must not share the event loop with request-plane latency)."""

    def __init__(self, host: str = "127.0.0.1",
                 block_provider: Callable[[int], np.ndarray | None] | None = None,
                 use_jax_path: bool | None = None):
        self.host = host
        self.port = 0
        self.block_provider = block_provider
        self._staged: dict[int, _Staged] = {}
        self._next_id = 1
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._running = False
        self._use_jax = (jax_transfer_usable() if use_jax_path is None
                         else use_jax_path)
        # Telemetry (tests + PERF_NOTES measurements).
        self.transfers = 0
        self.bytes_out = 0
        self.block_requests = 0
        self.blocks_served = 0

    def stats(self) -> dict:
        with self._lock:
            staged = len(self._staged)
        return {"transfers": self.transfers, "bytes_out": self.bytes_out,
                "block_requests": self.block_requests,
                "blocks_served": self.blocks_served, "staged": staged,
                "addr": self.address}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._running = True
        t = threading.Thread(target=self._accept_loop, name="kv-plane",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # Periodic GC: unclaimed tickets pin the extract's DEVICE buffer
        # through their resolve closure — a crashed sink must not hold
        # HBM past the TTL just because no new prefill triggers stage().
        g = threading.Thread(target=self._gc_loop, name="kv-plane-gc",
                             daemon=True)
        g.start()
        self._threads.append(g)
        log.info("KV plane listening on %s (jax path: %s)", self.address,
                 "on" if self._use_jax else "off")

    def _gc_loop(self) -> None:
        while self._running:
            time.sleep(min(30.0, STAGED_TTL_S / 4))
            with self._lock:
                self._gc_locked()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                # shutdown() first: a thread blocked in accept() holds a
                # kernel reference, so close() alone leaves the port
                # listening until the accept returns.
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            self._staged.clear()

    # -- staging ------------------------------------------------------------
    def stage(self, kv=None, meta: dict | None = None,
              resolve: Callable[[], np.ndarray] | None = None,
              device_array=None, prompt_len: int | None = None,
              resolve_groups: list | None = None) -> dict:
        """Stage a parcel; returns the transfer ticket to send over the
        (small) response stream. Either ``kv`` (host array), ``resolve``
        (deferred host fetch — lets the D2H copy overlap decode windows;
        resolved on the plane thread at pull time), or ``resolve_groups``
        ([(n_pages, resolver)] page groups streamed pipelined: group i's
        socket send overlaps group i+1's D2H) must be given.
        ``device_array`` additionally registers the parcel with the jax
        transfer server for a zero-host-copy pull when both ends support
        it."""
        meta = dict(meta or {})
        if kv is not None:
            meta.setdefault("shape", list(kv.shape))
            meta.setdefault("dtype", str(kv.dtype))
        shape, dt = meta["shape"], dtype_of(meta["dtype"])
        meta["nbytes"] = int(np.prod(shape)) * dt.itemsize
        if prompt_len is not None:
            meta["prompt_len"] = prompt_len
        with self._lock:
            tid = self._next_id
            self._next_id += 1
            jax_uuid = None
            if self._use_jax and device_array is not None:
                jax_uuid = tid
                try:
                    _get_jax_server().await_pull(jax_uuid, [device_array])
                except Exception:  # noqa: BLE001 — fall back to socket
                    log.exception("jax-path staging failed; socket only")
                    jax_uuid = None
            self._staged[tid] = _Staged(meta, kv, resolve, jax_uuid,
                                        groups=resolve_groups)
            self._gc_locked()
        ticket = {"id": tid, "addr": self.address, **meta}
        if jax_uuid is not None:
            ticket["jax_addr"] = _get_jax_server().address()
            ticket["jax_uuid"] = jax_uuid
        return ticket

    def _gc_locked(self) -> None:
        now = time.monotonic()
        dead = [tid for tid, s in self._staged.items()
                if now - s.t > STAGED_TTL_S]
        for tid in dead:
            del self._staged[tid]
        if dead:
            log.warning("expired %d unclaimed KV transfers", len(dead))

    # -- server loops --------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                try:
                    req = _recv_ctrl(conn)
                except (ConnectionError, OSError):
                    return
                op = req.get("op")
                if op == "pull":
                    self._handle_pull(conn, req)
                elif op == "blocks":
                    self._handle_blocks(conn, req)
                elif op == "done":
                    # Fire-and-forget: a jax-path pull completed — drop
                    # the staged entry now instead of pinning the device
                    # array until the TTL.
                    with self._lock:
                        self._staged.pop(int(req.get("id", -1)), None)
                else:
                    _send_ctrl(conn, {"err": f"unknown op {op!r}"})
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_pull(self, conn: socket.socket, req: dict) -> None:
        tid = int(req["id"])
        if chaos.ACTIVE:
            stall = chaos.value("kv.stall_ms", "kv")
            if stall is not None:
                time.sleep(stall / 1000.0)
            if chaos.fire("kv.pull_error", "kv"):
                _send_ctrl(conn, {"err": "chaos: injected pull error"})
                return
        busy = False
        with self._lock:
            staged = self._staged.get(tid)
            if staged is not None and staged.in_progress:
                # Another connection is already transmitting this ticket:
                # serving it twice would run grouped resolvers
                # concurrently and double-count transfer metrics.
                staged, busy = None, True
            elif staged is not None:
                staged.in_progress = True
        if staged is None:
            _send_ctrl(conn, {"err": "transfer already in progress" if busy
                              else "unknown or expired transfer id"})
            return
        # The entry stays staged until the bulk send COMPLETES: a
        # transient network failure mid-send would otherwise drop the
        # parcel permanently and force the sink to re-prefill locally
        # (its retry would see "expired transfer id"). The in_progress
        # claim is released on failure so that retry can win the ticket;
        # the TTL GC remains the backstop for sinks that never come back.
        served = False
        resolve_err: str | None = None
        try:
            served, resolve_err = self._transmit_staged(conn, staged)
        finally:
            # Release the claim BEFORE any error frame goes out: the sink
            # retries the moment it reads the error, and must not find
            # the ticket still claimed by this failed attempt.
            with self._lock:
                if served:
                    self._staged.pop(tid, None)
                else:
                    staged.in_progress = False
        if resolve_err is not None:
            _send_ctrl(conn, {"err": resolve_err})

    def _transmit_staged(self, conn: socket.socket,
                         staged: _Staged) -> tuple[bool, str | None]:
        """Resolve and send one staged parcel. Returns (served, err):
        served True only once every bulk byte is on the wire; err is a
        resolve-failure message for the caller to report AFTER releasing
        the in-progress claim."""
        if staged.groups is not None:
            # Pipelined page groups: group i rides the wire while group
            # i+1's D2H copy (dispatched at extract time) completes.
            try:
                first = np.ascontiguousarray(staged.groups[0][1]())
            except Exception as exc:  # noqa: BLE001
                log.exception("staged KV group resolve failed")
                return False, f"resolve failed: {exc}"
            _send_ctrl(conn, {"ok": True, **staged.meta,
                              "groups": [n for n, _ in staged.groups]})
            sent = first.nbytes
            _send_bulk(conn, first)
            for _, resolver in staged.groups[1:]:
                arr = np.ascontiguousarray(resolver())
                _send_bulk(conn, arr)
                sent += arr.nbytes
            self.transfers += 1
            self.bytes_out += sent
            return True, None
        try:
            arr = np.ascontiguousarray(staged.array())
        except Exception as exc:  # noqa: BLE001 — resolve() device fault
            log.exception("staged KV resolve failed")
            return False, f"resolve failed: {exc}"
        _send_ctrl(conn, {"ok": True, **staged.meta})
        if chaos.ACTIVE and chaos.fire("kv.partial", "kv"):
            # Send half the parcel, then sever: the sink's short read
            # must surface as a connection error and the parcel must
            # stay staged for its retry.
            data = memoryview(arr.view(np.uint8).reshape(-1))
            conn.sendall(data[:max(1, arr.nbytes // 2)])
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return False, None
        _send_bulk(conn, arr)
        self.transfers += 1
        self.bytes_out += arr.nbytes
        return True, None

    def _handle_blocks(self, conn: socket.socket, req: dict) -> None:
        """G4 remote-tier serve: return which of the requested block hashes
        this worker holds in its host tiers, with their bytes, stopping at
        the first miss (prefix semantics: later blocks are useless without
        earlier ones)."""
        self.block_requests += 1
        provider = self.block_provider
        hashes = [int(h) for h in req.get("hashes", [])]
        limit = int(req.get("max", 64))
        found: list[np.ndarray] = []
        found_hashes: list[int] = []
        if provider is not None:
            for h in hashes[:limit]:
                kv = provider(h)
                if kv is None:
                    break
                found.append(np.ascontiguousarray(kv))
                found_hashes.append(h)
        if not found:
            _send_ctrl(conn, {"ok": True, "hashes": [], "shape": [],
                              "dtype": "", "nbytes": 0})
            return
        stacked = np.stack(found)  # [n, 2, L, Nkv, page, D]
        _send_ctrl(conn, {"ok": True, "hashes": found_hashes,
                          "shape": list(stacked.shape),
                          "dtype": str(stacked.dtype),
                          "nbytes": stacked.nbytes})
        _send_bulk(conn, stacked)
        self.blocks_served += len(found)


class KvPlaneClient:
    """Sink side: pulls staged parcels / peer host-tier blocks. Blocking
    socket I/O runs on executor threads; per-address connections are
    cached (pulls from the same prefill worker reuse one TCP stream)."""

    def __init__(self, timeout: float = 30.0):
        # addr -> (socket, per-connection lock): pulls run on executor
        # threads, and two concurrent request/response cycles on one
        # socket would interleave frames — the lock serializes the full
        # cycle per connection. ``timeout`` bounds connect AND each recv:
        # callers on latency-sensitive threads (the engine's G4 consult)
        # pass a small value so a blackholed peer can't stall them long.
        self.timeout = timeout
        self._conns: dict[str, tuple[socket.socket, threading.Lock]] = {}
        self._lock = threading.Lock()
        self.transfers = 0
        self.bytes_in = 0
        self.jax_pulls = 0
        # Pull-latency aggregates (count + wall-clock sum): rate(sum)/
        # rate(count) is the fleet's mean pull latency on /metrics.
        self.pull_seconds_total = 0.0
        self.pull_failures = 0
        self._use_jax = None  # probed on first jax-path ticket

    def stats(self) -> dict:
        return {"transfers": self.transfers, "bytes_in": self.bytes_in,
                "jax_pulls": self.jax_pulls,
                "pull_seconds_total": self.pull_seconds_total,
                "pull_failures": self.pull_failures}

    # -- sync core (executor) ------------------------------------------------
    def _conn_for(self, addr: str) -> tuple[socket.socket, threading.Lock]:
        with self._lock:
            entry = self._conns.get(addr)
        if entry is not None:
            return entry
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self.timeout)
        sock.settimeout(self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._lock:
            old = self._conns.get(addr)
            if old is not None:
                sock.close()
                return old
            entry = (sock, threading.Lock())
            self._conns[addr] = entry
        return entry

    def _drop_conn(self, addr: str) -> None:
        with self._lock:
            entry = self._conns.pop(addr, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass

    def _pull_jax(self, ticket: dict) -> np.ndarray | None:
        if self._use_jax is None:
            self._use_jax = jax_transfer_usable()
        if not self._use_jax or "jax_addr" not in ticket:
            return None
        try:
            import jax
            from jax.sharding import SingleDeviceSharding

            conn = _get_jax_server().connect(ticket["jax_addr"])
            dev = jax.local_devices()[0]
            spec = jax.ShapeDtypeStruct(
                tuple(ticket["shape"]), dtype_of(ticket["dtype"]),
                sharding=SingleDeviceSharding(dev))
            out = conn.pull(int(ticket["jax_uuid"]), [spec])
            self.jax_pulls += 1
            return np.asarray(out[0])
        except Exception:  # noqa: BLE001 — fall through to the socket path
            log.exception("jax-path pull failed; falling back to socket")
            return None

    def pull_sync(self, ticket: dict) -> np.ndarray:
        t0 = time.monotonic()
        try:
            out = self._pull_sync_inner(ticket)
        except (ConnectionError, OSError):
            self.pull_failures += 1
            raise
        finally:
            self.pull_seconds_total += time.monotonic() - t0
        return out

    def _pull_sync_inner(self, ticket: dict) -> np.ndarray:
        out = self._pull_jax(ticket)
        if out is not None:
            self.transfers += 1
            try:  # release the server's staged entry (best-effort)
                sock, conn_lock = self._conn_for(ticket["addr"])
                with conn_lock:
                    _send_ctrl(sock, {"op": "done",
                                      "id": int(ticket["id"])})
            except (ConnectionError, OSError):
                pass  # TTL GC covers it
            return out
        # Transient failures (reset mid-transfer, a racing pull holding
        # the in-progress claim) retry through the unified policy — the
        # parcel stays staged on the source until every byte lands, so a
        # retry finds it. An expired/unknown ticket can never succeed:
        # fail fast and let the caller prefill locally.
        backoff = Backoff(policies.KV_PULL)
        while True:
            try:
                return self._pull_socket_once(ticket)
            except (ConnectionError, OSError) as exc:
                if "expired transfer" in str(exc) or not backoff.sleep_sync():
                    raise
                log.warning("KV pull failed (%s); retrying", exc)

    def _pull_socket_once(self, ticket: dict) -> np.ndarray:
        addr = ticket["addr"]
        sock, conn_lock = self._conn_for(addr)
        try:
            with conn_lock:
                _send_ctrl(sock, {"op": "pull", "id": int(ticket["id"])})
                resp = _recv_ctrl(sock)
                if "err" in resp:
                    raise ConnectionError(f"KV pull refused: {resp['err']}")
                shape = resp["shape"]
                dt = dtype_of(resp["dtype"])
                if "groups" in resp:
                    # Pipelined page groups along the pages axis (3):
                    # reassemble into the full parcel as they arrive.
                    full = np.empty(shape, dt)
                    off = 0
                    for g in resp["groups"]:
                        gshape = list(shape)
                        gshape[3] = g
                        buf = np.empty(
                            int(np.prod(gshape)) * dt.itemsize, np.uint8)
                        _recv_bulk_into(sock, memoryview(buf))
                        full[:, :, :, off:off + g] = \
                            buf.view(dt).reshape(gshape)
                        off += g
                    self.transfers += 1
                    self.bytes_in += full.nbytes
                    return full
                buf = np.empty(int(resp["nbytes"]), np.uint8)
                _recv_bulk_into(sock, memoryview(buf))
        except (ConnectionError, OSError):
            self._drop_conn(addr)
            raise
        self.transfers += 1
        self.bytes_in += buf.nbytes
        return buf.view(dt).reshape(shape)

    def fetch_blocks_sync(self, addr: str, hashes: list[int],
                          max_blocks: int = 64,
                          timeout: float | None = None
                          ) -> tuple[list[int], np.ndarray | None]:
        """G4: ask a peer for a consecutive run of block hashes from its
        host tiers. Returns (hashes found, [n, 2, L, Nkv, page, D]).
        ``timeout`` overrides the connection's per-recv timeout for this
        cycle (the G4 consult's overall deadline is the caller's)."""
        sock, conn_lock = self._conn_for(addr)
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        try:
            with conn_lock:
                if timeout is not None:
                    sock.settimeout(max(0.01, timeout))
                _send_ctrl(sock, {"op": "blocks", "hashes": hashes,
                                  "max": max_blocks})
                resp = _recv_ctrl(sock)
                if "err" in resp:
                    raise ConnectionError(
                        f"block fetch refused: {resp['err']}")
                if not resp["hashes"]:
                    if timeout is not None:
                        sock.settimeout(self.timeout)
                    return [], None
                dt = dtype_of(resp["dtype"])
                buf = np.empty(int(resp["nbytes"]), np.uint8)
                _recv_bulk_into(sock, memoryview(buf), deadline=deadline)
                if timeout is not None:
                    sock.settimeout(self.timeout)
        except (ConnectionError, OSError):
            self._drop_conn(addr)
            raise
        self.bytes_in += buf.nbytes
        return resp["hashes"], buf.view(dt).reshape(resp["shape"])

    # -- async wrappers ------------------------------------------------------
    async def pull(self, ticket: dict) -> np.ndarray:
        from dynamo_tpu.runtime.tracing import span

        with span("kv.plane.pull", ticket=ticket.get("id"),
                  nbytes=ticket.get("nbytes")):
            return await asyncio.get_running_loop().run_in_executor(
                None, self.pull_sync, ticket)

    async def fetch_blocks(self, addr: str, hashes: list[int],
                           max_blocks: int = 64):
        return await asyncio.get_running_loop().run_in_executor(
            None, self.fetch_blocks_sync, addr, hashes, max_blocks)

    def close(self) -> None:
        with self._lock:
            conns, self._conns = dict(self._conns), {}
        for sock, _ in conns.values():
            try:
                sock.close()
            except OSError:
                pass


class RemoteBlockSource:
    """G4 remote tier: fetch KV blocks from PEER workers' host tiers by
    block hash (reference CacheLevel G4, block_manager.rs:76-82 + the
    distributed leader/worker's cross-worker reuse role). The engine's
    KVBM consults it when a prefix extension misses G1/G2/G3 — one
    bounded round trip per peer, first hit wins; content-hashed blocks
    make the result trustworthy regardless of which worker computed
    them.

    ``peers`` is swapped wholesale by the worker's coordinator watcher
    (kvplane/ registrations), so the engine thread only ever reads a
    consistent list.

    Per-peer breaker discipline (runtime/retry.py): a failing peer
    opens for a cooldown that walks the G4_PEER_BREAKER policy curve —
    successive failures back off exponentially, a post-cooldown consult
    is the half-open probe, and one success resets the curve. Every
    consult outcome journals as a ``kv_peer_pull`` event
    (runtime/journal.py) so /debug/timeline shows cross-worker reuse —
    and its failures — as part of the fleet's decision history."""

    # G4 fetches run on the ENGINE thread between windows: the WHOLE
    # consult — every peer together — gets one sub-window budget, so
    # neither a dead peer nor a slow-but-alive one can stall unrelated
    # in-flight decode streams for more than ~one window period.
    # Recomputing the prefix is always the cheap safe fallback.
    G4_BUDGET_S = 0.1

    def __init__(self, client: KvPlaneClient | None = None,
                 self_addr: str | None = None, max_peers: int = 4,
                 budget_s: float | None = None):
        from dynamo_tpu.runtime.retry import policies
        self.budget_s = self.G4_BUDGET_S if budget_s is None else budget_s
        self.client = client or KvPlaneClient(timeout=self.budget_s)
        self.self_addr = self_addr
        self.max_peers = max_peers
        self.peers: list[str] = []
        self.breaker_policy = policies.G4_PEER_BREAKER
        self._cooldown: dict[str, float] = {}  # addr -> half-open time
        self._fail_streak: dict[str, int] = {}  # addr -> breaker curve pos
        self.fetched_blocks = 0
        self.fetch_failures = 0
        self.slow_peer_cooldowns = 0
        self.breaker_open_skips = 0   # consults skipped on open breakers

    def stats(self) -> dict:
        now = time.monotonic()
        return {"peers": len(self.peers),
                "fetched_blocks": self.fetched_blocks,
                "fetch_failures": self.fetch_failures,
                "slow_peer_cooldowns": self.slow_peer_cooldowns,
                "breaker_open_skips": self.breaker_open_skips,
                "breakers_open": sum(1 for t in self._cooldown.values()
                                     if t > now),
                **{f"client_{k}": v for k, v in self.client.stats().items()}}

    def _open_breaker(self, addr: str, reason: str) -> None:
        """One more failure on this peer: advance its breaker curve and
        cool it down for the policy's delay at that position (no jitter
        rng threading needed — the curve IS the discipline)."""
        streak = self._fail_streak.get(addr, 0)
        delay = self.breaker_policy.delay(streak)
        self._fail_streak[addr] = streak + 1
        self._cooldown[addr] = time.monotonic() + delay
        log.warning("G4 peer %s %s; breaker open %.1fs (streak %d)",
                    addr, reason, delay, streak + 1)

    def _note_success(self, addr: str) -> None:
        self._cooldown.pop(addr, None)
        self._fail_streak.pop(addr, None)

    def drop_peer(self, addr: str) -> None:
        """Fleet-membership hook (worker_leave / scale-in): forget the
        peer NOW — its address leaves the consult list and its breaker
        state dies with it, instead of waiting out staleness TTLs. A
        worker that later rejoins on the same address starts with a
        clean breaker rather than inheriting the dead incarnation's
        open curve."""
        self.peers = [a for a in self.peers if a != addr]
        self._cooldown.pop(addr, None)
        self._fail_streak.pop(addr, None)

    def fetch(self, hashes: list[int], max_blocks: int,
              trace_id: str | None = None) -> list[tuple[int, np.ndarray]]:
        """SYNC (engine thread, between windows): returns the longest
        consecutive run of requested blocks any single peer holds,
        giving the whole consult ``budget_s`` of wall clock."""
        from dynamo_tpu.runtime import journal
        from dynamo_tpu.runtime.journal import EventKind

        deadline = time.monotonic() + self.budget_s
        for addr in list(self.peers)[:self.max_peers]:
            if addr == self.self_addr or not addr:
                continue
            now = time.monotonic()
            remaining = deadline - now
            if remaining <= 0:
                break
            if self._cooldown.get(addr, 0.0) > now:
                self.breaker_open_skips += 1
                continue
            t0 = now
            try:
                found, arr = self.client.fetch_blocks_sync(
                    addr, hashes, max_blocks, timeout=remaining)
            except (ConnectionError, OSError) as exc:
                self.fetch_failures += 1
                slow = isinstance(exc, (socket.timeout, TimeoutError))
                if slow:
                    self.slow_peer_cooldowns += 1
                self._open_breaker(addr,
                                   "too slow" if slow else "unreachable")
                journal.emit(
                    EventKind.KV_PEER_PULL, trace_id=trace_id,
                    outcome="timeout" if slow else "error", peer=addr,
                    cause=journal.recent_ref(EventKind.CHAOS_INJECT))
                continue
            if time.monotonic() - t0 > self.budget_s:
                # Answered, but ate the whole consult budget: treat as
                # slow and stop consulting it for a while.
                self.slow_peer_cooldowns += 1
                self._open_breaker(addr, "consult overran budget")
            else:
                self._note_success(addr)
            if found:
                self.fetched_blocks += len(found)
                journal.emit(
                    EventKind.KV_PEER_PULL, trace_id=trace_id,
                    outcome="ok", peer=addr, blocks=len(found),
                    nbytes=int(arr.nbytes),
                    cause=journal.recent_ref(EventKind.KV_DEMOTE))
                return [(h, arr[i]) for i, h in enumerate(found)]
        return []
