"""Audio modality: WAV -> log-mel features -> encoder -> prompt embeddings.

The reference serves multimodal through per-engine processors
(components/backends/trtllm multimodal processor; examples/multimodal):
media is encoded OUTSIDE the LLM and injected as prompt embeddings. This
module is the TPU-native audio half of that contract:

- :func:`decode_wav` / :func:`log_mel_spectrogram` — stdlib/numpy
  feature extraction (16 kHz mono, 25 ms windows, 10 ms hop, 80 mels —
  the Whisper-style front end).
- :class:`AudioEncoder` — a small JAX conv-downsample + transformer
  encoder projecting frames to the target LLM's hidden size. Weights
  load from a safetensors file when provided, else deterministic random
  init (the serving PATH is what's exercised end to end; swapping in
  trained weights is a checkpoint question, not a code path question).
- :func:`embed_audio` — one call: wav bytes -> {"start", "b", "dtype",
  "shape"} span dict for ``PreprocessedRequest.mm_embeds``.

The engine side (prompt-embedding injection, placeholder ids, no-cache
handling) lives in engine/runner.py + engine/engine.py; the HTTP side
(/v1/audio/transcriptions) in llm/http_service.py.
"""

from __future__ import annotations

import dataclasses
import io
import wave

import numpy as np

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("audio")

SAMPLE_RATE = 16000
N_FFT = 400        # 25 ms @ 16 kHz
HOP = 160          # 10 ms
N_MELS = 80


def decode_wav(data: bytes) -> np.ndarray:
    """PCM WAV bytes -> float32 mono [-1, 1] at the file's rate, then
    naive-resampled to 16 kHz (linear interpolation — serving front
    ends resample upstream; this keeps the path dependency-free)."""
    with wave.open(io.BytesIO(data)) as wf:
        n = wf.getnframes()
        raw = wf.readframes(n)
        width = wf.getsampwidth()
        channels = wf.getnchannels()
        rate = wf.getframerate()
    if width == 2:
        audio = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 1:
        audio = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128) / 128
    elif width == 4:
        audio = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    if rate != SAMPLE_RATE:
        t_out = np.arange(int(len(audio) * SAMPLE_RATE / rate)) \
            * (rate / SAMPLE_RATE)
        audio = np.interp(t_out, np.arange(len(audio)), audio) \
            .astype(np.float32)
    return audio


def _mel_filterbank(n_mels: int, n_fft: int, sr: int) -> np.ndarray:
    """Triangular mel filters [n_mels, n_fft//2 + 1] (HTK mel scale)."""
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mel_pts = np.linspace(hz_to_mel(0.0), hz_to_mel(sr / 2), n_mels + 2)
    hz_pts = mel_to_hz(mel_pts)
    bins = np.floor((n_fft + 1) * hz_pts / sr).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for m in range(1, n_mels + 1):
        lo, c, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, c):
            if c > lo:
                fb[m - 1, k] = (k - lo) / (c - lo)
        for k in range(c, hi):
            if hi > c:
                fb[m - 1, k] = (hi - k) / (hi - c)
    return fb


_FB_CACHE: dict = {}


def log_mel_spectrogram(audio: np.ndarray) -> np.ndarray:
    """float32 mono 16 kHz -> log-mel frames [T, N_MELS]."""
    if len(audio) < N_FFT:
        audio = np.pad(audio, (0, N_FFT - len(audio)))
    n_frames = 1 + (len(audio) - N_FFT) // HOP
    window = np.hanning(N_FFT).astype(np.float32)
    frames = np.lib.stride_tricks.as_strided(
        audio, shape=(n_frames, N_FFT),
        strides=(audio.strides[0] * HOP, audio.strides[0]))
    spec = np.abs(np.fft.rfft(frames * window, axis=1)) ** 2
    key = (N_MELS, N_FFT, SAMPLE_RATE)
    if key not in _FB_CACHE:
        _FB_CACHE[key] = _mel_filterbank(*key)
    mel = spec @ _FB_CACHE[key].T
    logmel = np.log10(np.maximum(mel, 1e-10))
    return np.maximum(logmel, logmel.max() - 8.0).astype(np.float32)


@dataclasses.dataclass
class AudioEncoderSpec:
    n_mels: int = N_MELS
    d_model: int = 256
    num_layers: int = 2
    num_heads: int = 4
    downsample: int = 4  # frames per output embedding (2 conv stride-2)
    # "native": the TPU-first bf16 encoder below. "whisper": the exact
    # OpenAI Whisper encoder architecture (conv1 stride 1 + conv2 stride
    # 2, LayerNorm with bias, biased q/v/out/fc projections, concat
    # sin|cos positions, ln_post), run in fp32 — weights converted from
    # a real Whisper checkpoint by scripts/convert_whisper_encoder.py
    # compute the true Whisper encoding (golden-tested against the HF
    # implementation).
    arch: str = "native"


class AudioEncoder:
    """Conv-downsample + transformer encoder -> LLM hidden size.

    Two stride-2 1D convs (4x frame downsample: 80 mel frames/s ->
    20 embeddings/s), ``num_layers`` pre-norm self-attention blocks with
    sinusoidal positions, and a linear projection to ``llm_hidden``.
    Pure-functional JAX, jit-compiled per input-length bucket."""

    def __init__(self, llm_hidden: int,
                 spec: AudioEncoderSpec | None = None,
                 weights_path: str | None = None, seed: int = 0):
        import jax

        self.spec = spec or AudioEncoderSpec()
        self.llm_hidden = llm_hidden
        self.untrained = not weights_path  # surfaced in API responses
        if weights_path:
            self.params = self._load(weights_path)
        else:
            self.params = self._init(jax.random.key(seed))
        # jax.jit caches compilations per input shape itself; one wrapper
        # serves every length bucket (perf key=None: those per-bucket
        # compiles are expected, never flagged as recompiles).
        from dynamo_tpu.engine.perf import instrumented_jit

        self._fn = instrumented_jit("audio_encoder", self._forward)

    def _init(self, key):
        import jax
        import jax.numpy as jnp

        s = self.spec
        d = s.d_model
        keys = iter(jax.random.split(key, 8 + 4 * s.num_layers))

        def lin(k, i, o):
            return (jax.random.normal(k, (i, o), jnp.float32)
                    / np.sqrt(i)).astype(jnp.bfloat16)

        params = {
            "conv1": lin(next(keys), 3 * s.n_mels, d),   # kernel 3, stride 2
            "conv2": lin(next(keys), 3 * d, d),
            "proj": lin(next(keys), d, self.llm_hidden),
            "layers": [],
        }
        for _ in range(s.num_layers):
            params["layers"].append({
                "wq": lin(next(keys), d, d), "wk": lin(next(keys), d, d),
                "wv": lin(next(keys), d, d), "wo": lin(next(keys), d, d),
                "w1": lin(next(keys), d, 4 * d),
                "w2": lin(next(keys), 4 * d, d),
            })
        return params

    def _load(self, path: str):
        from safetensors import safe_open
        import ml_dtypes

        with safe_open(path, framework="numpy") as fh:
            raw = {k: fh.get_tensor(k) for k in fh.keys()}
        if any(k.startswith("whisper.") for k in raw):
            # Converted Whisper checkpoint: fp32, exact architecture.
            # meta[1] (when present) records whether the llm projection
            # is trained/lossless; a RANDOM projector still produces
            # babble and must keep the API warning.
            meta = raw["whisper.meta"]
            if len(meta) > 1 and not int(meta[1]):
                self.untrained = True
            proj = raw["whisper.proj"]
            if proj.shape[1] != self.llm_hidden:
                raise ValueError(
                    f"checkpoint projects to {proj.shape[1]}, model "
                    f"hidden is {self.llm_hidden}: re-run "
                    f"convert_whisper_encoder.py with --llm-hidden "
                    f"{self.llm_hidden}")
            self.spec = dataclasses.replace(
                self.spec, arch="whisper",
                n_mels=raw["whisper.conv1.w"].shape[0] // 3,
                d_model=raw["whisper.conv1.w"].shape[1],
                num_layers=max(
                    int(k.split(".")[2]) + 1 for k in raw
                    if k.startswith("whisper.layers.")),
                num_heads=int(raw["whisper.meta"][0]),
                downsample=2)
            f32 = {k: v.astype(np.float32, copy=False)
                   for k, v in raw.items()}
            params = {k[len("whisper."):]: f32[k] for k in f32
                      if not k.startswith("whisper.layers.")
                      and k != "whisper.meta"}
            params["layers"] = []
            for i in range(self.spec.num_layers):
                pre = f"whisper.layers.{i}."
                params["layers"].append(
                    {k[len(pre):]: f32[k] for k in f32
                     if k.startswith(pre)})
            return params
        flat = {k: v.astype(ml_dtypes.bfloat16) for k, v in raw.items()}
        params = {"conv1": flat["conv1"], "conv2": flat["conv2"],
                  "proj": flat["proj"], "layers": []}
        i = 0
        while f"layers.{i}.wq" in flat:
            params["layers"].append(
                {k: flat[f"layers.{i}.{k}"]
                 for k in ("wq", "wk", "wv", "wo", "w1", "w2")})
            i += 1
        return params

    def _forward_whisper(self, params, mel):
        """Exact Whisper encoder forward (fp32): gelu(conv1 s1) ->
        gelu(conv2 s2) -> +sinusoid positions -> pre-norm blocks with
        biased q/v/out/fc projections (k unbiased, q scaled) -> ln_post
        -> llm projection. Golden-tested against the HF implementation
        (tests/test_audio.py)."""
        import jax
        import jax.numpy as jnp

        s = self.spec
        nh = s.num_heads
        d = s.d_model
        hd = d // nh

        def conv(x, w, b, cin, stride):
            t_out = x.shape[0] // stride
            xp = jnp.pad(x, ((1, 1), (0, 0)))
            idx0 = jnp.arange(t_out) * stride
            win = jnp.stack([xp[idx0], xp[idx0 + 1], xp[idx0 + 2]],
                            axis=1)                       # [t, 3, cin]
            return jax.nn.gelu(win.reshape(t_out, 3 * cin) @ w + b)

        def ln(h, w, b):
            m = h.mean(-1, keepdims=True)
            v = ((h - m) ** 2).mean(-1, keepdims=True)
            return (h - m) * jax.lax.rsqrt(v + 1e-5) * w + b

        x = conv(mel.astype(jnp.float32), params["conv1.w"],
                 params["conv1.b"], s.n_mels, 1)
        x = conv(x, params["conv2.w"], params["conv2.b"], d, 2)
        t = x.shape[0]
        x = x + params["pos"][:t]
        for lp in params["layers"]:
            h = ln(x, lp["ln1.w"], lp["ln1.b"])
            q = ((h @ lp["wq"] + lp["bq"]) * (hd ** -0.5)) \
                .reshape(t, nh, hd)
            k = (h @ lp["wk"]).reshape(t, nh, hd)
            v = (h @ lp["wv"] + lp["bv"]).reshape(t, nh, hd)
            scores = jnp.einsum("qnd,knd->nqk", q, k)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("nqk,knd->qnd", probs, v).reshape(t, d)
            x = x + (attn @ lp["wo"] + lp["bo"])
            h2 = ln(x, lp["ln2.w"], lp["ln2.b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
        x = ln(x, params["ln_post.w"], params["ln_post.b"])
        return (x @ params["proj"]).astype(jnp.float32)

    def _forward(self, params, mel):
        import jax
        import jax.numpy as jnp

        if self.spec.arch == "whisper":
            return self._forward_whisper(params, mel)

        s = self.spec
        d = s.d_model

        def conv_s2(x, w, cin):
            # kernel-3 stride-2 conv as a strided window matmul.
            t = x.shape[0] // 2
            xp = jnp.pad(x, ((1, 1), (0, 0)))
            win = jnp.stack([xp[0:2 * t:2], xp[1:2 * t + 1:2],
                             xp[2:2 * t + 2:2]], axis=1)  # [t, 3, cin]
            return jax.nn.gelu(win.reshape(t, 3 * cin) @ w)

        x = conv_s2(mel.astype(jnp.bfloat16), params["conv1"], s.n_mels)
        x = conv_s2(x, params["conv2"], d)
        t = x.shape[0]
        pos = jnp.arange(t)[:, None] / (10000 ** (
            jnp.arange(d)[None, :] / d))
        x = x + jnp.where(jnp.arange(d)[None, :] % 2 == 0,
                          jnp.sin(pos), jnp.cos(pos)).astype(jnp.bfloat16)

        def norm(h):
            hf = h.astype(jnp.float32)
            var = jnp.mean(hf * hf, axis=-1, keepdims=True)
            return (hf * jax.lax.rsqrt(var + 1e-5)).astype(h.dtype)

        nh = s.num_heads
        hd = d // nh
        for lp in params["layers"]:
            h = norm(x)
            q = (h @ lp["wq"]).reshape(t, nh, hd)
            k = (h @ lp["wk"]).reshape(t, nh, hd)
            v = (h @ lp["wv"]).reshape(t, nh, hd)
            scores = jnp.einsum("qnd,knd->nqk", q, k,
                                preferred_element_type=jnp.float32)
            probs = jax.nn.softmax(scores / np.sqrt(hd), axis=-1) \
                .astype(jnp.bfloat16)
            attn = jnp.einsum("nqk,knd->qnd", probs, v).reshape(t, d)
            x = x + attn @ lp["wo"]
            x = x + jax.nn.gelu(norm(x) @ lp["w1"]) @ lp["w2"]
        return (norm(x) @ params["proj"]).astype(jnp.float32)

    def encode(self, mel: np.ndarray) -> np.ndarray:
        """log-mel [T, n_mels] -> embeddings [T // downsample, llm_hidden]
        (length-bucketed compile cache; pad frames are trimmed)."""
        import jax
        import jax.numpy as jnp

        t = mel.shape[0]
        bucket = 64
        while bucket < t:
            bucket *= 2
        padded = np.zeros((bucket, mel.shape[1]), np.float32)
        padded[:t] = mel
        out = np.asarray(self._fn(self.params, jnp.asarray(padded)))
        return out[:max(1, t // self.spec.downsample)]


def embed_audio(wav_bytes: bytes, encoder: AudioEncoder,
                start: int = 0) -> tuple[dict, int]:
    """WAV bytes -> (mm_embeds span dict at ``start``, span length)."""
    mel = log_mel_spectrogram(decode_wav(wav_bytes))
    emb = encoder.encode(mel)
    return ({"start": start, "b": emb.astype(np.float32).tobytes(),
             "dtype": "float32", "shape": list(emb.shape)}, emb.shape[0])
