"""Built-in test engines.

Capability parity with reference EchoFull/EchoCore (lib/llm/src/engines.rs:31-44):
token-level echo engines used to exercise the full pipeline with no model. The
TPU-timing simulator lives in dynamo_tpu.llm.mocker.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine


class EchoEngine(AsyncEngine):
    """Echoes the prompt token ids back one token per response, bounded by
    max_tokens, with a configurable per-token delay (engines.rs EchoFull's
    DELAY)."""

    def __init__(self, token_delay_s: float = 0.0):
        self.token_delay_s = token_delay_s

    async def generate(self, request, context: Context
                       ) -> AsyncIterator[dict]:
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        budget = req.stop_conditions.max_tokens or len(req.token_ids)
        tokens = req.token_ids[:budget] or [0]
        for i, tid in enumerate(tokens):
            if context.is_stopped:
                yield LLMEngineOutput(token_ids=[],
                                      finish_reason=FinishReason.CANCELLED).to_wire()
                return
            if self.token_delay_s:
                await asyncio.sleep(self.token_delay_s)
            finish = FinishReason.LENGTH if i == len(tokens) - 1 else None
            yield LLMEngineOutput(token_ids=[tid],
                                  finish_reason=finish).to_wire()

    def handler(self):
        """serve_endpoint-compatible async-generator handler."""

        async def handle(request, context):
            if isinstance(request, dict) and request.get("embed"):
                raise ValueError("echo engine does not serve embeddings")
            async for out in self.generate(request, context):
                yield out

        return handle
