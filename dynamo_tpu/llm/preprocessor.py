"""OpenAI preprocessor: requests -> tokens, engine outputs -> SSE deltas.

Capability parity with reference OpenAIPreprocessor (lib/llm/src/
preprocessor.rs:92-143 preprocess_request; :358 transform_postprocessor_stream):
forward direction renders the chat template (jinja2, reference uses minijinja),
tokenizes, and applies sampling/stop defaulting into a PreprocessedRequest;
backward direction turns LLMEngineOutput streams into OpenAI
chat.completion.chunk / text_completion deltas with usage and finish reasons.
Annotations (formatted_prompt, token_ids) mirror preprocessor.rs annotations.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

import jinja2

from dynamo_tpu.llm.model_card import DEFAULT_CHAT_TEMPLATE, ModelDeploymentCard
from dynamo_tpu.llm.protocols import (
    ChatCompletionRequest,
    CompletionRequest,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
    chat_completion_id,
    completion_id,
    now_unix,
    usage_block,
)
from dynamo_tpu.llm.tokenizer import Tokenizer
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine, Operator


class OpenAIPreprocessor(Operator):
    def __init__(self, card: ModelDeploymentCard, tokenizer: Tokenizer,
                 inner: AsyncEngine | None = None):
        super().__init__(inner)
        self.card = card
        self.tokenizer = tokenizer
        self._jinja = jinja2.Environment()
        self._template = self._jinja.from_string(
            card.chat_template or DEFAULT_CHAT_TEMPLATE)
        self.eos_ids = tokenizer.eos_token_ids()

    # -- forward: OpenAI -> PreprocessedRequest ------------------------------
    def apply_chat_template(self, request: ChatCompletionRequest) -> str:
        messages = [{"role": m.role, "content": m.text_content()}
                    for m in request.messages]
        return self._template.render(messages=messages, add_generation_prompt=True)

    def preprocess_chat(self, request: ChatCompletionRequest
                        ) -> PreprocessedRequest:
        prompt = self.apply_chat_template(request)
        token_ids = self.tokenizer.encode(prompt)
        images = self._collect_images(request)
        if not images:
            return self._build(request.model, token_ids, request, prompt)
        # Image modality (reference examples/multimodal, image-first):
        # encoder embeddings PREPEND as placeholder-token spans (llava
        # convention) and ride mm_embeds through the same injection path
        # as audio — disagg, no-cache, and chunk handling compose
        # identically (llm/vision.py).
        encoder = self._vision_encoder()
        from dynamo_tpu.llm.vision import embed_image
        spans = []
        offset = 0
        for img_bytes in images:
            span, n = embed_image(img_bytes, encoder, start=offset)
            spans.append(span)
            offset += n
        pre = self._build(request.model, [0] * offset + token_ids,
                          request, prompt)
        pre.mm_embeds = spans
        if encoder.untrained:
            pre.annotations["vision_encoder"] = "untrained-random-init"
        return pre

    @staticmethod
    def _has_images(request: ChatCompletionRequest) -> bool:
        """Cheap predicate (no base64 decoding on the event loop)."""
        return any(part.get("type") == "image_url"
                   for m in request.messages
                   if isinstance(m.content, list) for part in m.content)

    @staticmethod
    def _collect_images(request: ChatCompletionRequest) -> list[bytes]:
        from dynamo_tpu.llm.vision import data_uri_bytes
        out = []
        for m in request.messages:
            if isinstance(m.content, list):
                for part in m.content:
                    if part.get("type") == "image_url":
                        url = (part.get("image_url") or {}).get("url", "")
                        out.append(data_uri_bytes(url))
        return out

    def _vision_encoder(self):
        enc = getattr(self, "_vision_enc", None)
        if enc is None:
            import os

            from dynamo_tpu.llm.vision import VisionEncoder
            hidden = (self.card.runtime_config.extra or {}) \
                .get("hidden_size")
            if hidden is None:
                raise ValueError(
                    f"model {self.card.name!r} did not publish "
                    "hidden_size; image input needs an embedding-capable "
                    "worker")
            weights = (os.environ.get("DTPU_VISION_ENCODER_WEIGHTS")
                       or (self.card.runtime_config.extra or {})
                       .get("vision_encoder_weights"))
            enc = self._vision_enc = VisionEncoder(
                int(hidden), weights_path=weights)
        return enc

    def preprocess_completion(self, request: CompletionRequest
                              ) -> PreprocessedRequest:
        prompt_in = request.prompt
        if isinstance(prompt_in, list) and prompt_in and isinstance(
                prompt_in[0], str):
            if len(prompt_in) > 1:
                # Batch prompts need one choice per element; reject loudly
                # rather than silently concatenating.
                raise ValueError(
                    "batch prompts (list of strings) are not supported; send "
                    "one request per prompt")
            prompt_in = prompt_in[0]
        if isinstance(prompt_in, list):
            token_ids = list(prompt_in)
            prompt = None
        else:
            prompt = prompt_in
            token_ids = self.tokenizer.encode(prompt)
        return self._build(request.model, token_ids, request, prompt)

    def _build(self, model: str, token_ids: list[int], request,
               formatted_prompt: str | None) -> PreprocessedRequest:
        max_tokens = (getattr(request, "max_completion_tokens", None)
                      or request.max_tokens)
        if max_tokens is None:
            # Default to remaining context (reference defaults from the card).
            max_tokens = max(1, self.card.context_length - len(token_ids))
        stop = StopConditions(
            max_tokens=max_tokens,
            min_tokens=request.min_tokens,
            stop=request.stop_list(),
            ignore_eos=bool(request.ignore_eos),
        )
        # logprobs: chat uses bool logprobs + int top_logprobs; the legacy
        # completion API uses an int. Normalize to "None = off, k = chosen
        # token + k alternatives".
        lp_req = getattr(request, "logprobs", None)
        if isinstance(lp_req, bool):
            logprobs_n = (getattr(request, "top_logprobs", None) or 0) \
                if lp_req else None
        else:
            logprobs_n = lp_req
        sampling = SamplingOptions(
            temperature=request.temperature,
            top_p=request.top_p,
            top_k=getattr(request, "top_k", None),
            frequency_penalty=getattr(request, "frequency_penalty", None),
            presence_penalty=getattr(request, "presence_penalty", None),
            seed=request.seed,
            n=request.n,
            logprobs=logprobs_n,
        )
        annotations: dict[str, Any] = {}
        if formatted_prompt is not None:
            annotations["formatted_prompt"] = formatted_prompt
        # Multi-tenant LoRA: an adapter card (register_adapter) names the
        # base model it rides on — the OpenAI ``model`` field resolved to
        # THIS card, so the wire request carries the adapter explicitly
        # and the worker maps it to a resident slot (engine/lora.py).
        extra = (self.card.runtime_config.extra or {})
        adapter = extra.get("adapter") if extra.get("lora_base") else None
        return PreprocessedRequest(
            model=model, token_ids=token_ids, stop_conditions=stop,
            sampling_options=sampling, eos_token_ids=self.eos_ids,
            annotations=annotations, adapter=adapter)

    # -- operator interface ---------------------------------------------------
    async def generate(self, request: ChatCompletionRequest,
                       context: Context) -> AsyncIterator[dict]:
        """Full chat pipeline edge: forward preprocess, stream deltas back."""
        assert self.inner is not None, "preprocessor not linked to an engine"
        if self._has_images(request):
            # base64 decode + image encode (and its first jit compile)
            # run for seconds on CPU frontends: off the event loop, or
            # every concurrent SSE stream on this frontend freezes.
            import asyncio
            pre = await asyncio.to_thread(self.preprocess_chat, request)
        else:
            pre = self.preprocess_chat(request)
        delta_gen = ChatDeltaGenerator(
            request, prompt_tokens=len(pre.token_ids),
            tool_call_parser=self.card.tool_call_parser,
            reasoning_parser=self.card.reasoning_parser)
        inner_iter = self.inner.generate(pre, context)
        async for out in inner_iter:
            engine_out = (out if isinstance(out, LLMEngineOutput)
                          else LLMEngineOutput.from_wire(out))
            for chunk in delta_gen.step(engine_out):
                yield chunk

    async def generate_completion(self, request: CompletionRequest,
                                  context: Context) -> AsyncIterator[dict]:
        """Text-completion pipeline edge (mirrors the chat edge so the HTTP
        layer never reaches into pipeline internals)."""
        assert self.inner is not None, "preprocessor not linked to an engine"
        pre = self.preprocess_completion(request)
        delta_gen = CompletionDeltaGenerator(request,
                                             prompt_tokens=len(pre.token_ids))
        async for out in self.inner.generate(pre, context):
            engine_out = (out if isinstance(out, LLMEngineOutput)
                          else LLMEngineOutput.from_wire(out))
            for chunk in delta_gen.step(engine_out):
                yield chunk


class ChatDeltaGenerator:
    """LLMEngineOutput stream -> OpenAI chat.completion.chunk dicts
    (reference DeltaGenerator, preprocessor.rs:358-460). When the model
    card names parsers, think-tags split into reasoning_content deltas and
    tool-call payloads are jailed out of the content stream and emitted as
    tool_calls at finish (finish_reason becomes "tool_calls")."""

    def __init__(self, request: ChatCompletionRequest, prompt_tokens: int,
                 tool_call_parser: str | None = None,
                 reasoning_parser: str | None = None):
        from dynamo_tpu.llm.parsers import (StreamingReasoningParser,
                                            StreamingToolCallParser)
        self.id = chat_completion_id()
        self.model = request.model
        self.created = now_unix()
        self.prompt_tokens = prompt_tokens
        self.completion_tokens = 0
        self.include_usage = bool(
            (request.stream_options or {}).get("include_usage"))
        self._first = True
        self._reasoning = (StreamingReasoningParser(reasoning_parser)
                           if reasoning_parser else None)
        self._tools = (StreamingToolCallParser(tool_call_parser)
                       if tool_call_parser else None)

    def _base(self) -> dict:
        return {"id": self.id, "object": "chat.completion.chunk",
                "created": self.created, "model": self.model}

    def step(self, out: LLMEngineOutput) -> list[dict]:
        chunks: list[dict] = []
        self.completion_tokens += len(out.token_ids)
        delta: dict[str, Any] = {}
        if self._first:
            delta["role"] = "assistant"
            self._first = False
        content = out.text or ""
        reasoning = ""
        if self._reasoning is not None and content:
            content, reasoning = self._reasoning.feed(content)
        if self._tools is not None and content:
            content = self._tools.feed(content)
        finish = out.finish_reason.to_openai() if out.finish_reason else None
        if finish:
            if self._reasoning is not None:
                tail_c, tail_r = self._reasoning.finish()
                if self._tools is not None and tail_c:
                    tail_c = self._tools.feed(tail_c)
                content += tail_c
                reasoning += tail_r
            if self._tools is not None:
                trailing, calls = self._tools.finish()
                content += trailing
                if calls:
                    delta["tool_calls"] = [c.to_openai(i)
                                           for i, c in enumerate(calls)]
                    finish = "tool_calls"
        if content:
            delta["content"] = content
        if reasoning:
            delta["reasoning_content"] = reasoning
        lp_block = None
        if out.log_probs is not None:
            entries = []
            texts = out.token_texts or [""] * len(out.log_probs)
            tops = out.top_log_probs or [[]] * len(out.log_probs)
            for t_text, lp, alts in zip(texts, out.log_probs, tops):
                entries.append({
                    "token": t_text, "logprob": lp, "bytes": None,
                    "top_logprobs": [
                        {"token": a.get("token", ""),
                         "logprob": a["logprob"], "bytes": None}
                        for a in alts]})
            lp_block = {"content": entries}
        if delta or finish or lp_block:
            # lp_block alone still emits: tokens whose text is held back
            # (stop-prefix/tool jail) must not lose their logprobs.
            chunk = self._base()
            chunk["choices"] = [{"index": 0, "delta": delta,
                                 "logprobs": lp_block,
                                 "finish_reason": finish}]
            chunks.append(chunk)
        if finish and self.include_usage:
            usage_chunk = self._base()
            usage_chunk["choices"] = []
            usage_chunk["usage"] = usage_block(self.prompt_tokens,
                                              self.completion_tokens)
            chunks.append(usage_chunk)
        return chunks


class CompletionDeltaGenerator:
    """LLMEngineOutput stream -> OpenAI text_completion chunks."""

    def __init__(self, request: CompletionRequest, prompt_tokens: int):
        self.id = completion_id()
        self.model = request.model
        self.created = now_unix()
        self.prompt_tokens = prompt_tokens
        self.completion_tokens = 0
        self.include_usage = bool(
            (request.stream_options or {}).get("include_usage"))

    def step(self, out: LLMEngineOutput) -> list[dict]:
        self.completion_tokens += len(out.token_ids)
        finish = out.finish_reason.to_openai() if out.finish_reason else None
        chunks = []
        lp_block = None
        if out.log_probs is not None:
            # Legacy completions logprobs shape.
            lp_block = {
                "tokens": out.token_texts or [],
                "token_logprobs": out.log_probs,
                "top_logprobs": [
                    {a.get("token", ""): a["logprob"] for a in alts}
                    for alts in (out.top_log_probs or [])],
                "text_offset": [],
            }
        if out.text or finish or lp_block:
            chunks.append({
                "id": self.id, "object": "text_completion",
                "created": self.created, "model": self.model,
                "choices": [{"index": 0, "text": out.text or "",
                             "finish_reason": finish, "logprobs": lp_block}],
            })
        if finish and self.include_usage:
            chunks.append({
                "id": self.id, "object": "text_completion",
                "created": self.created, "model": self.model, "choices": [],
                "usage": usage_block(self.prompt_tokens, self.completion_tokens),
            })
        return chunks


async def aggregate_chat_stream(chunks: AsyncIterator[dict],
                                prompt_tokens: int) -> dict:
    """Fold a chunk stream into a non-streaming chat.completion response
    (reference protocols/openai/chat_completions/aggregator.rs)."""
    content: list[str] = []
    reasoning: list[str] = []
    tool_calls: list[dict] = []
    lp_entries: list[dict] = []
    role = "assistant"
    finish_reason = None
    cid = None
    model = None
    created = None
    usage = None
    completion_tokens = 0
    async for chunk in chunks:
        cid = chunk.get("id", cid)
        model = chunk.get("model", model)
        created = chunk.get("created", created)
        if chunk.get("usage"):
            usage = chunk["usage"]
        for choice in chunk.get("choices", []):
            delta = choice.get("delta", {})
            if delta.get("content"):
                content.append(delta["content"])
            if delta.get("reasoning_content"):
                reasoning.append(delta["reasoning_content"])
            if delta.get("tool_calls"):
                tool_calls.extend(delta["tool_calls"])
            if delta.get("role"):
                role = delta["role"]
            if choice.get("logprobs"):
                lp_entries.extend(choice["logprobs"].get("content") or [])
            if choice.get("finish_reason"):
                finish_reason = choice["finish_reason"]
    message: dict[str, Any] = {"role": role, "content": "".join(content)}
    if reasoning:
        message["reasoning_content"] = "".join(reasoning)
    if tool_calls:
        message["tool_calls"] = tool_calls
        message["content"] = message["content"] or None
    return {
        "id": cid, "object": "chat.completion", "created": created,
        "model": model,
        "choices": [{"index": 0, "message": message,
                     "logprobs": ({"content": lp_entries}
                                  if lp_entries else None),
                     "finish_reason": finish_reason}],
        "usage": usage or usage_block(prompt_tokens, completion_tokens),
    }
