"""Pre-warmed standby workers: the worker side of fleet autoscaling.

Cold-starting a TPU worker costs minutes (weight load + warmup-ladder
compiles) — useless against a traffic spike the SLO plane detects in
seconds. A **standby** worker pays all of that up front and then parks:
weights loaded, warmup ladder run, but **deregistered** — no model
card, no endpoint registrations, invisible to routers. It announces
itself on a lease-bound ``standby/`` key and waits for one verb.

Coordinator schema (same shape as the role-flip protocol in
llm/reconfig.py)::

    standby/<namespace>/<worker_hex> -> standby status (worker's lease)
    scale/<namespace>/<worker_hex>   -> ScaleDirective (issuer's lease)

A ``ScaleDirective`` is ``{"action": "promote"|"retire", "role", "epoch",
"issued_by", "cause", "drain_s"?}``:

- **promote** (standby only): the worker journals ``standby_promote``
  (caused by the planner's decision ref riding the directive), drops
  its ``standby/`` key, and starts its RoleManager — building the
  serving profile and registering endpoints, which is what makes the
  frontend's discovery emit ``worker_join``. The worker also journals
  its own ``worker_join`` (caused by the promote) so the chain
  ``planner_decision -> standby_promote -> worker_join`` is walkable in
  the merged timeline even before any frontend notices. Join latency
  (promote directive -> serving) lands in ``standby_join_seconds``.
- **retire** (scale-in): delegated to ``RoleManager.retire()`` — the
  SAME lock and epoch fence as SetRole, so a scale-in racing a role
  flip resolves to exactly one winner (the loser rejects typed). The
  drain deregisters first and kills leftovers with typed
  ``incomplete:scale_in`` frames that migrate; on completion the
  worker main's shutdown hook fires and the process exits, taking its
  lease (and every lease-bound key) with it. A retire aimed at a
  still-parked standby simply shrinks the pool: journal, drop the key,
  shut down — there is nothing to drain.

Epoch fencing is SHARED with role flips: the planner's FleetScaler and
RoleReconfigurator both mint epochs strictly above everything visible
in the fleet (rolestatus + role/ + scale/ directives), and the worker
applies whichever verb wins the fence. Directives ride the ISSUER's
lease: a planner that dies after issuing loses the key, so a stale
scale-out can never apply later.

Crash safety: a standby that dies mid-join loses its lease — the
``standby/`` key and any half-made registrations vanish, the planner's
next step sees an orphaned promote directive (no standby, no
rolestatus), reaps it, and promotes a replacement
(planner/capacity.py).
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable

from dynamo_tpu.llm.reconfig import RoleManager
from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.errors import RoleTransitionError
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, policies

log = get_logger("standby")

STANDBY_ROOT = "standby/"
SCALE_ROOT = "scale/"

#: The scale-directive verbs (anything else is malformed and ignored).
SCALE_ACTIONS = ("promote", "retire")


def standby_key(namespace: str, worker_id: int) -> str:
    """The lease-bound key a parked standby announces itself on."""
    return f"{STANDBY_ROOT}{namespace}/{worker_id:x}"


def scale_key(namespace: str, worker_id: int) -> str:
    """The directive key the worker watches for promote/retire verbs."""
    return f"{SCALE_ROOT}{namespace}/{worker_id:x}"


class StandbyState:
    """ScaleAgent lifecycle (docs/RESILIENCE.md "Autoscaling")."""

    WARMING = "warming"
    READY = "ready"        # parked: warmed, deregistered, lease held
    PROMOTING = "promoting"
    ACTIVE = "active"      # serving (RoleManager started)
    RETIRED = "retired"


class ScaleAgent:
    """One worker's scale-directive intake, in either launch mode.

    ``standby=True`` parks the worker (runs ``warmup``, publishes the
    ``standby/`` key, does NOT start the RoleManager); ``standby=False``
    is a normal serving worker that still answers retire verbs so the
    planner can scale it in. The worker main starts the RoleManager
    itself in non-standby mode, exactly as before this module existed.
    """

    def __init__(self, runtime, roles: RoleManager, standby: bool = False,
                 namespace: str | None = None,
                 warmup: Callable | None = None,
                 status_extra: dict | None = None,
                 on_shutdown: Callable | None = None,
                 metrics=None):
        self._runtime = runtime
        self.roles = roles
        self.namespace = namespace or runtime.config.namespace
        self.standby = standby
        self._warmup = warmup
        self._extra = dict(status_extra or {})
        # What a completed retire runs (default: stop the process, so
        # the lease — and every lease-bound key — dies with it).
        self._on_shutdown = on_shutdown or runtime.shutdown
        self.state = StandbyState.ACTIVE
        self.join_seconds: float | None = None
        self.promotions = 0
        self._watch = None
        self._watch_task: asyncio.Task | None = None
        self._m_ready = self._m_promos = self._m_join = None
        if metrics is not None:
            m = metrics.namespace("standby")
            self._m_ready = m.gauge(
                "standby_ready",
                "1 while this worker is a parked pre-warmed standby")
            self._m_promos = m.counter(
                "standby_promotions_total",
                "Standby -> serving promotions on this worker")
            self._m_join = m.gauge(
                "standby_join_seconds",
                "Last promote-directive-to-serving join latency")

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        if self.roles._on_retired is None:
            self.roles._on_retired = self._shutdown
        if self.standby:
            self.state = StandbyState.WARMING
            if self._warmup is not None:
                res = self._warmup()
                if asyncio.iscoroutine(res):
                    await res
            self.state = StandbyState.READY
            await self._write_standby()
            if self._m_ready is not None:
                self._m_ready.set(1.0)
            journal.emit(EventKind.STANDBY_READY,
                         worker_id=f"{self._runtime.instance_id:x}",
                         **self._extra)
            log.info("standby parked (warmed, deregistered): %x",
                     self._runtime.instance_id)
        if self._runtime.has_discovery:
            client = self._runtime.require_coordinator()
            client.on_lease_recreated(self._on_lease_recreated)
            self._watch = await client.watch_prefix(
                scale_key(self.namespace, self._runtime.instance_id))
            for item in self._watch.snapshot:
                await self._apply(item["v"])
            self._watch_task = asyncio.create_task(self._watch_loop())

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch is not None:
            await self._watch.cancel()

    def _shutdown(self) -> None:
        try:
            self._on_shutdown()
        except Exception:  # noqa: BLE001 — a broken hook must not wedge
            log.exception("scale-in shutdown hook failed")

    async def _on_lease_recreated(self, _new_lease_id: int) -> None:
        if self.state in (StandbyState.WARMING, StandbyState.READY):
            await self._write_standby()

    # -- directive intake ------------------------------------------------------
    async def _apply(self, value) -> None:
        if not isinstance(value, dict) or value.get("action") \
                not in SCALE_ACTIONS:
            log.warning("malformed scale directive ignored: %r", value)
            return
        try:
            if value["action"] == "promote":
                await self._promote(value)
            else:
                await self._retire(value)
        except RoleTransitionError as exc:
            # Fencing rejections are normal under replay/races; the
            # typed decision is already journaled by the fence.
            log.info("scale directive fenced out: %s", exc)
        except (ValueError, TypeError) as exc:
            log.warning("malformed scale directive ignored: %s", exc)

    async def _watch_loop(self) -> None:
        """Same survival contract as the role-directive watch: anything
        short of cancellation re-establishes, or the worker would ignore
        the planner forever."""
        backoff = Backoff(policies.COORD_RECONNECT)
        while True:
            try:
                async for event in self._watch:
                    if event["event"] == "put":
                        await self._apply(event["value"])
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — re-establish, never die
                log.exception("scale directive watch failed; re-watching")
            await backoff.sleep()
            try:
                self._watch = await self._runtime.require_coordinator() \
                    .watch_prefix(scale_key(self.namespace,
                                            self._runtime.instance_id))
                for item in self._watch.snapshot:
                    await self._apply(item["v"])
                backoff.reset()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("scale directive re-watch failed; will retry")

    # -- promote ---------------------------------------------------------------
    async def _promote(self, directive: dict) -> None:
        epoch = int(directive.get("epoch", 0))
        if self.state == StandbyState.ACTIVE:
            # Replay of the promote that already ran (watch reconnect
            # snapshot), or a planner re-issue that raced our join: a
            # noop either way — but fence FORWARD so the planner's GC
            # sees the directive applied and reaps it instead of
            # counting it as an action in flight forever.
            if epoch > self.roles.applied_epoch:
                self.roles.applied_epoch = epoch
                await self.roles._write_status()
                log.info("promote epoch %d on an already-active worker: "
                         "fenced forward", epoch)
            return
        if self.state != StandbyState.READY:
            log.info("promote while %s ignored", self.state)
            return
        if epoch <= self.roles.applied_epoch:
            log.info("stale promote epoch %d fenced (applied %d)",
                     epoch, self.roles.applied_epoch)
            return
        role = directive.get("role") or self.roles.role
        self.state = StandbyState.PROMOTING
        t0 = time.monotonic()
        promote_ref = journal.emit(
            EventKind.STANDBY_PROMOTE, cause=directive.get("cause"),
            worker_id=f"{self._runtime.instance_id:x}", role=role,
            epoch=epoch, issued_by=directive.get("issued_by", "?"))
        # Drop the standby key FIRST: the pool shrinks the moment the
        # promote starts, so a second scale-out can't double-book this
        # worker. If the join dies after this point the planner sees an
        # orphaned directive (no standby, no rolestatus) and promotes a
        # replacement.
        try:
            await self._runtime.require_coordinator().kv_delete(
                standby_key(self.namespace, self._runtime.instance_id))
        except (ConnectionError, OSError, RuntimeError):
            log.warning("standby key delete failed (coordinator down?); "
                        "lease expiry will reap it")
        if self._m_ready is not None:
            self._m_ready.set(0.0)
        self.roles.role = role
        self.roles.applied_epoch = epoch
        # The join must ride out a coordinator outage: a standby that
        # gave up mid-registration would be stuck — not parked, not
        # serving — forever. Transient transport errors retry under the
        # unified reconnect policy; real build bugs propagate.
        backoff = Backoff(policies.COORD_RECONNECT)
        while True:
            try:
                await self.roles.start()
                break
            except (ConnectionError, OSError, asyncio.TimeoutError):
                log.warning("standby join hit a transport error; "
                            "retrying", exc_info=True)
                # An attempt can fail AFTER the profile built (e.g. the
                # directive watch dial): tear the partial profile down
                # or the retry would register duplicate servers.
                if self.roles.profile is not None:
                    for server in self.roles.profile.servers:
                        try:
                            await server.shutdown()
                        except Exception:  # noqa: BLE001 — best effort
                            pass
                    await self.roles.profile.close()
                    self.roles.profile = None
                await backoff.sleep()
        self.join_seconds = time.monotonic() - t0
        self.promotions += 1
        self.state = StandbyState.ACTIVE
        if self._m_promos is not None:
            self._m_promos.inc()
        if self._m_join is not None:
            self._m_join.set(self.join_seconds)
        journal.emit(EventKind.WORKER_JOIN, cause=promote_ref,
                     instance=f"{self._runtime.instance_id:x}",
                     via="standby", role=role,
                     join_seconds=round(self.join_seconds, 3))
        log.info("standby promoted to %s in %.2fs (epoch %d)", role,
                 self.join_seconds, epoch)

    # -- retire ----------------------------------------------------------------
    async def _retire(self, directive: dict) -> None:
        epoch = int(directive.get("epoch", 0))
        if self.state in (StandbyState.WARMING, StandbyState.READY,
                          StandbyState.PROMOTING):
            # Shrinking the standby pool: nothing serves, nothing drains.
            if epoch <= self.roles.applied_epoch:
                return
            self.roles.applied_epoch = epoch
            self.state = StandbyState.RETIRED
            journal.emit(EventKind.SCALE_RETIRE,
                         cause=directive.get("cause"), phase="standby",
                         epoch=epoch, outcome="ok")
            try:
                await self._runtime.require_coordinator().kv_delete(
                    standby_key(self.namespace, self._runtime.instance_id))
            except (ConnectionError, OSError, RuntimeError):
                pass
            if self._m_ready is not None:
                self._m_ready.set(0.0)
            self._shutdown()
            return
        await self.roles.retire(
            epoch, issued_by=str(directive.get("issued_by", "directive")),
            drain_s=directive.get("drain_s"),
            cause=directive.get("cause"))
        self.state = StandbyState.RETIRED

    # -- status ----------------------------------------------------------------
    def standby_status(self) -> dict:
        return {
            "worker": f"{self._runtime.instance_id:x}",
            "state": self.state,
            "role": self.roles.role,
            "warmed": self.state in (StandbyState.READY,
                                     StandbyState.PROMOTING,
                                     StandbyState.ACTIVE),
            "ts": time.time(),
            **self._extra,
        }

    async def _write_standby(self) -> None:
        try:
            await self._runtime.require_coordinator().kv_put(
                standby_key(self.namespace, self._runtime.instance_id),
                self.standby_status(), use_primary_lease=True)
        except (ConnectionError, OSError, RuntimeError):
            log.warning("standby status write failed (coordinator "
                        "down?); will replay on reconnect")
