"""LLM domain layer (capability parity with reference lib/llm).

OpenAI-compatible HTTP service, preprocessor (templating + tokenization),
detokenizing backend, migration, KV-aware router, model cards/discovery, and
the simulation ("mocker") engine. The actual TPU engine lives in
``dynamo_tpu.engine``.
"""
