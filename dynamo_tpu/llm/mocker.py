"""Mocker: a TPU-engine simulator with real KV events and metrics.

Capability parity with reference lib/llm/src/mocker (~3.3K LoC): a faithful
continuous-batching simulation — waiting/prefill/decode scheduling with token
budgets (mocker/scheduler.rs), a paged KV cache with prefix reuse and LRU
eviction that emits real stored/removed KV events (mocker/kv_manager.rs), and
ForwardPassMetrics publishing — so KV-aware routing, overload, replica sync,
and migration are testable with zero TPUs (mocker/protocols.rs:79-104
speedup_ratio/num_gpu_blocks args). The timing model approximates a TPU chip:
prefill at a fixed tok/s, decode steps at a fixed latency per batch iteration.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from collections import OrderedDict
from typing import AsyncIterator

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, KvStats, WorkerStats
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.llm.tokens import TokenBlockSequence
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_recorder

log = get_logger("mocker")


@dataclasses.dataclass
class MockerConfig:
    num_kv_blocks: int = 1024
    block_size: int = 16
    max_num_seqs: int = 64
    max_batched_tokens: int = 8192
    prefill_tokens_per_s: float = 100_000.0
    decode_step_s: float = 0.005
    speedup_ratio: float = 1.0  # reference mocker/protocols.rs:79
    # Simulated host (G2) tier: evicted blocks land here instead of
    # vanishing, stay out of the radix index (their removed events
    # fire) but in the inventory digest — the substrate KV federation
    # routing/peer-pull tests need, with zero TPUs (docs/OBSERVABILITY
    # "KV federation"). 0 disables (pre-federation behavior).
    host_blocks: int = 0

    def prefill_time(self, tokens: int) -> float:
        return tokens / self.prefill_tokens_per_s / self.speedup_ratio

    def decode_time(self) -> float:
        return self.decode_step_s / self.speedup_ratio


class KvCacheSim:
    """Paged KV cache simulation with prefix reuse + LRU eviction
    (reference mocker/kv_manager.rs). Emits stored/removed hashes via the
    events lists drained by the engine loop."""

    def __init__(self, capacity: int, host_capacity: int = 0):
        self.capacity = capacity
        # block_hash -> refcount; insertion order refreshed on use = LRU.
        self._blocks: OrderedDict[int, int] = OrderedDict()
        # Simulated G2 host tier: eviction victims demote here (LRU,
        # bounded); an admit that misses G1 but hits here ONBOARDS the
        # block back (promote-on-hit) instead of "recomputing".
        self.host_capacity = host_capacity
        self.host: OrderedDict[int, bool] = OrderedDict()
        self.host_onboards = 0
        self.host_spills = 0
        self.peer_onboards = 0
        self.stored_events: list[int] = []
        self.removed_events: list[int] = []

    def lookup_prefix(self, hashes: list[int]) -> int:
        """Longest cached prefix (cache hit blocks) for a new sequence,
        across G1 and the host-tier sim (a host block onboards during
        allocate() instead of 'recomputing' — it counts as a hit).
        Refreshes recency of the G1 hits."""
        n = 0
        for h in hashes:
            if h in self._blocks:
                self._blocks.move_to_end(h)
                n += 1
            elif h in self.host:
                n += 1
            else:
                break
        return n

    def allocate(self, hashes: list[int]) -> bool:
        """Pin all blocks of ``hashes`` (allocating misses). False if the pool
        can't fit even after evicting unpinned blocks."""
        wanted = set(hashes)
        misses = [h for h in hashes if h not in self._blocks]
        free_needed = len(self._blocks) + len(misses) - self.capacity
        if free_needed > 0 and not self._evict(free_needed, protect=wanted):
            return False
        for h in hashes:
            if h in self._blocks:
                self._blocks[h] += 1
                self._blocks.move_to_end(h)
            else:
                if self.host.pop(h, None) is not None:
                    # Promote-on-hit from the simulated host tier.
                    self.host_onboards += 1
                self._blocks[h] = 1
                self.stored_events.append(h)
        return True

    def _evict(self, count: int, protect: set[int] = frozenset()) -> bool:
        """Evict ``count`` unpinned LRU blocks, never touching ``protect``
        (the request being allocated — evicting its own reusable blocks would
        overflow capacity and emit bogus removed+stored event pairs)."""
        victims = [h for h, ref in self._blocks.items()
                   if ref == 0 and h not in protect]
        if len(victims) < count:
            return False
        for h in victims[:count]:
            del self._blocks[h]
            self.removed_events.append(h)
            if self.host_capacity > 0:
                # Demote to the host-tier sim instead of dropping.
                self.host[h] = True
                self.host.move_to_end(h)
                self.host_spills += 1
                while len(self.host) > self.host_capacity:
                    self.host.popitem(last=False)
        return True

    def inject(self, h: int) -> None:
        """A peer-pulled block lands as a reusable (unpinned) local
        block — allocate() then counts it as a hit instead of a miss."""
        if h not in self._blocks:
            self._blocks[h] = 0
            self.stored_events.append(h)
            self.peer_onboards += 1

    def append_block(self, h: int) -> bool:
        """Allocate one new pinned block for a decoding sequence."""
        return self.allocate([h]) if h not in self._blocks else self._pin(h)

    def _pin(self, h: int) -> bool:
        self._blocks[h] += 1
        self._blocks.move_to_end(h)
        return True

    def release(self, hashes: list[int]) -> None:
        """Unpin (blocks stay cached for prefix reuse until evicted)."""
        for h in hashes:
            if h in self._blocks and self._blocks[h] > 0:
                self._blocks[h] -= 1

    @property
    def active_blocks(self) -> int:
        return sum(1 for ref in self._blocks.values() if ref > 0)

    @property
    def cached_blocks(self) -> int:
        return len(self._blocks)


class _Seq:
    def __init__(self, req: PreprocessedRequest, ctx: Context, block_size: int):
        self.req = req
        self.ctx = ctx
        # One item per generated token, capped by the request's
        # max_tokens budget in _emit_token.
        # dtpu: ignore[unbounded-queue] -- bounded by max_tokens
        self.out_q: asyncio.Queue = asyncio.Queue()
        self.blocks = TokenBlockSequence(block_size, req.token_ids)
        self.generated = 0
        self.prefill_done_at: float | None = None
        self.cached_prefix_blocks = 0
        # Tracing phase boundaries (monotonic).
        self.enqueue_mono = time.monotonic()
        self.prefill_mono: float | None = None
        self.decode_mono: float | None = None


class MockerEngine(AsyncEngine):
    def __init__(self, config: MockerConfig | None = None,
                 kv_publisher=None, metrics_publisher=None,
                 inventory_publisher=None):
        self.config = config or MockerConfig()
        self.kv = KvCacheSim(self.config.num_kv_blocks,
                             self.config.host_blocks)
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        self.inventory_publisher = inventory_publisher
        # G4 peer tier (kv_plane.RemoteBlockSource), set by the worker
        # main when a KV plane runs: blocks the fleet holds but this
        # worker lacks are "pulled" (real plane round trip; the sim
        # discards the bytes and counts the block as onboarded).
        self.remote_source = None
        self.waiting: list[_Seq] = []
        self.prefilling: list[_Seq] = []
        self.decoding: list[_Seq] = []
        self._loop_task: asyncio.Task | None = None
        self._wake = asyncio.Event()
        self.prefix_hits = 0
        self.prefix_lookups = 0

    def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._engine_loop())

    async def stop(self) -> None:
        if self._loop_task:
            self._loop_task.cancel()
            self._loop_task = None

    # -- engine interface -----------------------------------------------------
    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        self.start()
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        seq = _Seq(req, context, self.config.block_size)
        self.waiting.append(seq)
        self._wake.set()
        while True:
            item = await seq.out_q.get()
            if item is None:
                return
            yield item
            if item.get("finish_reason"):
                return

    def handler(self):
        async def handle(request, context):
            if isinstance(request, dict) and request.get("embed"):
                raise ValueError("mocker engine does not serve embeddings")
            async for out in self.generate(request, context):
                yield out

        return handle

    # -- simulation loop ------------------------------------------------------
    async def _engine_loop(self) -> None:
        cfg = self.config
        while True:
            if not (self.waiting or self.prefilling or self.decoding):
                self._wake.clear()
                # Idle engine parks until generate() sets the wake event;
                # idling forever with no requests is the contract.
                # dtpu: ignore[unbounded-wait] -- see above
                await self._wake.wait()
            now = time.monotonic()
            if self.remote_source is not None and self.waiting:
                await self._peer_consult()
            self._admit(now)
            # Complete prefills whose simulated time has elapsed.
            for seq in list(self.prefilling):
                if now >= seq.prefill_done_at:
                    self.prefilling.remove(seq)
                    self.decoding.append(seq)
                    rec = get_recorder()
                    if rec.enabled and seq.prefill_mono is not None:
                        rec.add("engine.prefill", seq.ctx.trace_id,
                                seq.ctx.span_id, seq.prefill_mono,
                                time.monotonic(),
                                attrs={"prompt_tokens": len(seq.req.token_ids),
                                       "cached_blocks":
                                       seq.cached_prefix_blocks})
                    seq.decode_mono = time.monotonic()
                    # First token is produced by the prefill itself.
                    self._emit_token(seq)
            # One decode iteration for the whole batch.
            if self.decoding:
                await asyncio.sleep(cfg.decode_time())
                for seq in list(self.decoding):
                    self._emit_token(seq)
            else:
                await asyncio.sleep(cfg.decode_time())
            try:
                await self._flush_events()
                await self._publish_metrics()
                await self._publish_inventory()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — publishing must not
                # kill the simulation loop (requests would hang forever).
                log.warning("mocker publish failed: %s", exc)

    async def _peer_consult(self) -> None:
        """G4 consult for the queue head: the run of blocks past the
        local prefix (G1 + host sim) is fetched from peers over the
        REAL KV plane (executor — the blocking socket round trip must
        not sit on the event loop); fetched blocks inject as reusable
        local blocks so _admit counts them as hits. One consult per
        sequence (the flag), recompute is the silent fallback."""
        seq = self.waiting[0]
        if getattr(seq, "peer_consulted", False):
            return
        seq.peer_consulted = True
        hashes = seq.blocks.block_hashes
        local = 0
        for h in hashes:
            if h in self.kv._blocks or h in self.kv.host:
                local += 1
            else:
                break
        want = hashes[local:]
        if not want:
            return
        loop = asyncio.get_running_loop()
        try:
            fetched = await loop.run_in_executor(
                None, self.remote_source.fetch, want, len(want))
        except Exception:  # noqa: BLE001 — peers are best-effort
            log.warning("mocker peer consult failed", exc_info=True)
            return
        for h, _ in fetched:
            self.kv.inject(h)

    def _admit(self, now: float) -> None:
        cfg = self.config
        while self.waiting and (len(self.prefilling) + len(self.decoding)
                                < cfg.max_num_seqs):
            seq = self.waiting[0]
            if seq.ctx.is_killed:
                self.waiting.pop(0)
                seq.out_q.put_nowait(None)
                continue
            hashes = seq.blocks.block_hashes
            self.prefix_lookups += 1
            cached = self.kv.lookup_prefix(hashes)
            if not self.kv.allocate(hashes):
                break  # no KV room: stays waiting
            if cached:
                self.prefix_hits += 1
            seq.cached_prefix_blocks = cached
            new_tokens = len(seq.req.token_ids) - cached * cfg.block_size
            self.waiting.pop(0)
            rec = get_recorder()
            if rec.enabled:
                rec.add("engine.queue_wait", seq.ctx.trace_id,
                        seq.ctx.span_id, seq.enqueue_mono, time.monotonic())
            seq.prefill_mono = time.monotonic()
            seq.prefill_done_at = now + cfg.prefill_time(max(0, new_tokens))
            self.prefilling.append(seq)

    def _emit_token(self, seq: _Seq) -> None:
        cfg = self.config
        if seq.ctx.is_killed:
            self._finish(seq, None)
            return
        if seq.ctx.is_stopped:
            self._finish(seq, FinishReason.CANCELLED)
            return
        # Deterministic "generation": echo prompt tokens cyclically.
        prompt = seq.req.token_ids or [0]
        token = prompt[seq.generated % len(prompt)]
        new_block = seq.blocks.append(token)
        if new_block is not None:
            self.kv.append_block(new_block)
        seq.generated += 1
        budget = seq.req.stop_conditions.max_tokens or 16
        finish = FinishReason.LENGTH if seq.generated >= budget else None
        seq.out_q.put_nowait(LLMEngineOutput(
            token_ids=[token], finish_reason=finish).to_wire())
        if finish:
            self._finish(seq, None)

    def _finish(self, seq: _Seq, reason: FinishReason | None) -> None:
        if seq in self.decoding:
            self.decoding.remove(seq)
        rec = get_recorder()
        if rec.enabled and seq.decode_mono is not None:
            rec.add("engine.decode", seq.ctx.trace_id, seq.ctx.span_id,
                    seq.decode_mono, time.monotonic(),
                    attrs={"tokens": seq.generated})
            seq.decode_mono = None
        self.kv.release(seq.blocks.block_hashes)
        if reason is not None:
            seq.out_q.put_nowait(LLMEngineOutput(
                token_ids=[], finish_reason=reason).to_wire())
        else:
            seq.out_q.put_nowait(None)

    async def _flush_events(self) -> None:
        if self.kv_publisher is None:
            self.kv.stored_events.clear()
            self.kv.removed_events.clear()
            return
        if self.kv.stored_events:
            stored, self.kv.stored_events = self.kv.stored_events, []
            await self.kv_publisher.stored(stored)
        if self.kv.removed_events:
            removed, self.kv.removed_events = self.kv.removed_events, []
            await self.kv_publisher.removed(removed)

    async def _publish_metrics(self) -> None:
        if self.metrics_publisher is None:
            return
        cfg = self.config
        active = len(self.prefilling) + len(self.decoding)
        hit_rate = (self.prefix_hits / self.prefix_lookups
                    if self.prefix_lookups else 0.0)
        # Force the transition-to-idle publish past the throttle, otherwise
        # routers keep seeing the last busy snapshot forever.
        force = active == 0 and not self.waiting
        await self.metrics_publisher.publish(ForwardPassMetrics(
            worker_stats=WorkerStats(
                request_active_slots=active,
                request_total_slots=cfg.max_num_seqs,
                num_requests_waiting=len(self.waiting)),
            kv_stats=KvStats(
                kv_active_blocks=self.kv.active_blocks,
                kv_total_blocks=cfg.num_kv_blocks,
                gpu_cache_usage_perc=self.kv.active_blocks / cfg.num_kv_blocks,
                gpu_prefix_cache_hit_rate=hit_rate)), force=force)

    # -- KV observability (docs/OBSERVABILITY.md "KV & capacity") -------------
    def host_block_provider(self, block_hash: int):
        """KvPlaneServer ``blocks`` provider: serve any block this
        worker holds (G1 or the host sim) to peer pulls, as a tiny
        placeholder parcel (the sim's content is its hash). Runs on a
        plane connection thread — dict lookups racing the loop degrade
        to a miss, never an error."""
        import numpy as np
        try:
            held = (block_hash in self.kv._blocks
                    or block_hash in self.kv.host)
        except RuntimeError:  # mutated mid-lookup: treat as miss
            held = False
        return np.full((2, 1, 1, 8), block_hash & 0xFFFF,
                       np.float32) if held else None

    def inventory_digest(self):
        """Same digest shape the TPU engine publishes, from the
        simulated block pool (fleet-pane tests without hardware). The
        sketch covers the host-tier sim too — the federated router's
        view of blocks that left the radix index on eviction."""
        from dynamo_tpu.llm.kv_router.protocols import (KvInventoryDigest,
                                                        kmin_sketch)
        cfg = self.config
        hashes = list(self.kv._blocks.keys())
        tier_blocks = {"g1": len(hashes)}
        host_hashes = list(self.kv.host.keys())
        if self.kv.host_capacity > 0:
            tier_blocks["g2"] = len(host_hashes)
        return KvInventoryDigest(
            blocks=len(hashes),
            tier_blocks=tier_blocks,
            pages_total=cfg.num_kv_blocks,
            pages_free=cfg.num_kv_blocks - self.kv.active_blocks,
            pages_active=self.kv.active_blocks,
            sketch=kmin_sketch(hashes + host_hashes))

    async def _publish_inventory(self) -> None:
        if self.inventory_publisher is None:
            return
        loop = asyncio.get_running_loop()
        if self.inventory_publisher.due(loop.time()):
            await self.inventory_publisher.publish(self.inventory_digest())

    def kv_status(self) -> dict:
        """The /debug/kv body for a mocker worker."""
        cfg = self.config
        return {
            "role": "mocker",
            "allocator": {
                "pages_total": cfg.num_kv_blocks,
                "pages_free": cfg.num_kv_blocks - self.kv.active_blocks,
                "pages_active": self.kv.active_blocks,
                "pages_inactive": self.kv.cached_blocks
                - self.kv.active_blocks,
                "cached_blocks": self.kv.cached_blocks,
                "occupancy": self.kv.active_blocks / cfg.num_kv_blocks,
                "reuse_hit_blocks": self.prefix_hits,
                "reuse_lookup_blocks": self.prefix_lookups,
            },
            "tiers": ({"g2_blocks": len(self.kv.host),
                       "g2_capacity": self.kv.host_capacity,
                       "g2_spills_in": self.kv.host_spills,
                       "g2_onboards": self.kv.host_onboards}
                      if self.kv.host_capacity > 0 else {}),
            "reuse": {"prefix_hit_blocks": self.prefix_hits,
                      "prefix_lookup_blocks": self.prefix_lookups,
                      "onboard_blocks_peer": self.kv.peer_onboards},
            "plane": None,
            "remote": (self.remote_source.stats()
                       if self.remote_source is not None else None),
            "digest": self.inventory_digest().to_wire(),
        }

    def perf_status(self) -> dict:
        """The /debug/perf body for a mocker worker: the process-global
        compile observatory (empty of device programs — mockers never
        jit) so the fleet pane's perf merge is exercisable without
        hardware."""
        from dynamo_tpu.engine.perf import get_registry
        reg = get_registry()
        return {"role": "mocker", "compiles": reg.snapshot(),
                "window": reg.window_snapshot(), "hbm": {}, "memory": {},
                "roofline": {"frac": reg.roofline_frac,
                             "expected_frac": None}}
