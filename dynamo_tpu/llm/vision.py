"""Image modality: image bytes -> patch embeddings -> prompt-embedding
spans (mm_embeds) injected into the prefill.

Role parity with the reference's image-first multimodal examples
(examples/multimodal, components/backends/trtllm multimodal processor):
the frontend decodes and encodes media, the LLM worker consumes
placeholder tokens whose embeddings are overridden by encoder output —
the same modality-agnostic injection path the audio modality uses
(llm/audio.py), so disagg/no-cache/chunk handling compose identically.

TPU-first: the encoder is a pure-functional JAX ViT (patchify as one
reshape+matmul onto the MXU, pre-norm attention blocks, jit-compiled;
fixed 224x224 input so there is exactly one compiled shape). Weights
load from a safetensors file (DTPU_VISION_ENCODER_WEIGHTS or the model
card's runtime extras) and default to deterministic random init,
flagged ``untrained`` — mapping patches into a text LLM's prompt space
needs a jointly-trained projector, which no public checkpoint provides
for arbitrary LLMs (same caveat as the audio encoder, stated rather
than hidden).
"""

from __future__ import annotations

import dataclasses
import io

import numpy as np

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("vision")

IMAGE_SIZE = 224
# CLIP-convention normalization (public-domain constants).
_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


def decode_image(data: bytes, size: int = IMAGE_SIZE) -> np.ndarray:
    """Image bytes (PNG/JPEG/...) -> [size, size, 3] float32,
    CLIP-normalized. Bilinear resize; alpha dropped."""
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    img = img.resize((size, size), Image.BILINEAR)
    arr = np.asarray(img, np.float32) / 255.0
    return (arr - _MEAN) / _STD


@dataclasses.dataclass
class VisionEncoderSpec:
    patch: int = 16
    d_model: int = 256
    num_layers: int = 2
    num_heads: int = 4
    image_size: int = IMAGE_SIZE
    # "native": the TPU-first bf16 ViT below. "clip": the exact CLIP
    # vision transformer (CLS token + learned positions, pre_layernorm,
    # biased q/k/v/out/fc with quick_gelu), run in fp32 — weights
    # converted from a real CLIP checkpoint by
    # scripts/convert_clip_vision.py compute the true CLIP patch
    # features (golden-tested vs the HF implementation offline).
    arch: str = "native"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


class VisionEncoder:
    """Patchify -> linear embed + 2D sinusoidal positions -> pre-norm
    transformer blocks -> projection to the LLM hidden size."""

    def __init__(self, llm_hidden: int,
                 spec: VisionEncoderSpec | None = None,
                 weights_path: str | None = None, seed: int = 0):
        import jax

        self.spec = spec or VisionEncoderSpec()
        self.llm_hidden = llm_hidden
        self.untrained = not weights_path
        if weights_path:
            self.params = self._load(weights_path)
        else:
            self.params = self._init(jax.random.key(seed))
        # key=None: self-bucketing program (jit caches per input shape;
        # new image sizes compile legitimately, never flagged).
        from dynamo_tpu.engine.perf import instrumented_jit
        self._fn = instrumented_jit("vision_encoder", self._forward)

    def _init(self, key):
        import jax
        import jax.numpy as jnp

        s = self.spec
        d = s.d_model
        pdim = 3 * s.patch * s.patch
        keys = iter(jax.random.split(key, 2 + 6 * s.num_layers))

        def lin(k, i, o):
            return (jax.random.normal(k, (i, o), jnp.float32)
                    / np.sqrt(i)).astype(jnp.bfloat16)

        params = {"patch": lin(next(keys), pdim, d),
                  "proj": lin(next(keys), d, self.llm_hidden),
                  "layers": []}
        for _ in range(s.num_layers):
            params["layers"].append({
                "wq": lin(next(keys), d, d), "wk": lin(next(keys), d, d),
                "wv": lin(next(keys), d, d), "wo": lin(next(keys), d, d),
                "w1": lin(next(keys), d, 4 * d),
                "w2": lin(next(keys), 4 * d, d),
            })
        return params

    def _load(self, path: str):
        from safetensors import safe_open
        import ml_dtypes

        with safe_open(path, framework="numpy") as fh:
            raw = {k: fh.get_tensor(k) for k in fh.keys()}
        if any(k.startswith("clip.") for k in raw):
            # Converted CLIP checkpoint: fp32, exact architecture.
            meta = raw["clip.meta"]  # [num_heads, patch, proj_trained]
            if len(meta) > 2 and not int(meta[2]):
                self.untrained = True
            proj = raw["clip.proj"]
            if proj.shape[1] != self.llm_hidden:
                raise ValueError(
                    f"checkpoint projects to {proj.shape[1]}, model "
                    f"hidden is {self.llm_hidden}: re-run "
                    f"convert_clip_vision.py with --llm-hidden "
                    f"{self.llm_hidden}")
            d = raw["clip.patch"].shape[1]
            n_layers = max(int(k.split(".")[2]) + 1 for k in raw
                           if k.startswith("clip.layers."))
            patch = int(meta[1])
            # Grid size comes from the learned position table (CLS + g^2).
            g = int(round((raw["clip.pos"].shape[0] - 1) ** 0.5))
            self.spec = dataclasses.replace(
                self.spec, arch="clip", d_model=d, patch=patch,
                num_layers=n_layers, num_heads=int(meta[0]),
                image_size=g * patch)
            f32 = {k: v.astype(np.float32, copy=False)
                   for k, v in raw.items()}
            params = {k[len("clip."):]: f32[k] for k in f32
                      if not k.startswith("clip.layers.")
                      and k != "clip.meta"}
            params["layers"] = []
            for i in range(n_layers):
                pre = f"clip.layers.{i}."
                params["layers"].append(
                    {k[len(pre):]: f32[k] for k in f32
                     if k.startswith(pre)})
            return params
        flat = {k: v.astype(ml_dtypes.bfloat16) for k, v in raw.items()}
        params = {"patch": flat["patch"], "proj": flat["proj"],
                  "layers": []}
        i = 0
        while f"layers.{i}.wq" in flat:
            params["layers"].append(
                {k: flat[f"layers.{i}.{k}"]
                 for k in ("wq", "wk", "wv", "wo", "w1", "w2")})
            i += 1
        return params

    def _forward_clip(self, params, img):
        """Exact CLIP vision transformer forward (fp32): patchify ->
        CLS + learned positions -> pre_layernorm -> pre-norm blocks with
        biased projections and quick_gelu -> patch tokens (CLS dropped,
        matching HF last_hidden_state[:, 1:]) -> llm projection."""
        import jax
        import jax.numpy as jnp

        s = self.spec
        d = s.d_model
        p = s.patch
        g = s.image_size // p
        nh = s.num_heads
        hd = d // nh

        def ln(h, w, b):
            m = h.mean(-1, keepdims=True)
            v = ((h - m) ** 2).mean(-1, keepdims=True)
            return (h - m) * jax.lax.rsqrt(v + 1e-5) * w + b

        def quick_gelu(x):
            return x * jax.nn.sigmoid(1.702 * x)

        patches = img.astype(jnp.float32) \
            .reshape(g, p, g, p, 3).transpose(0, 2, 1, 3, 4) \
            .reshape(g * g, p * p * 3) @ params["patch"]
        x = jnp.concatenate([params["cls"][None], patches], axis=0)
        t = x.shape[0]
        x = x + params["pos"][:t]
        x = ln(x, params["pre_ln.w"], params["pre_ln.b"])
        for lp in params["layers"]:
            h = ln(x, lp["ln1.w"], lp["ln1.b"])
            q = ((h @ lp["wq"] + lp["bq"]) * (hd ** -0.5)) \
                .reshape(t, nh, hd)
            k = (h @ lp["wk"] + lp["bk"]).reshape(t, nh, hd)
            v = (h @ lp["wv"] + lp["bv"]).reshape(t, nh, hd)
            scores = jnp.einsum("qnd,knd->nqk", q, k)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("nqk,knd->qnd", probs, v).reshape(t, d)
            x = x + (attn @ lp["wo"] + lp["bo"])
            h2 = ln(x, lp["ln2.w"], lp["ln2.b"])
            x = x + (quick_gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
        return (x[1:] @ params["proj"]).astype(jnp.float32)

    def _forward(self, params, img):
        import jax
        import jax.numpy as jnp

        if self.spec.arch == "clip":
            return self._forward_clip(params, img)

        s = self.spec
        d = s.d_model
        p = s.patch
        g = s.image_size // p
        # Patchify: [H, W, 3] -> [g*g, p*p*3] in one reshape/transpose.
        x = img.reshape(g, p, g, p, 3).transpose(0, 2, 1, 3, 4) \
            .reshape(g * g, p * p * 3).astype(jnp.bfloat16)
        x = x @ params["patch"]
        t = x.shape[0]
        pos = jnp.arange(t)[:, None] / (10000 ** (
            jnp.arange(d)[None, :] / d))
        x = x + jnp.where(jnp.arange(d)[None, :] % 2 == 0,
                          jnp.sin(pos), jnp.cos(pos)).astype(jnp.bfloat16)

        def norm(h):
            hf = h.astype(jnp.float32)
            var = jnp.mean(hf * hf, axis=-1, keepdims=True)
            return (hf * jax.lax.rsqrt(var + 1e-5)).astype(h.dtype)

        nh = s.num_heads
        hd = d // nh
        for lp in params["layers"]:
            h = norm(x)
            q = (h @ lp["wq"]).reshape(t, nh, hd)
            k = (h @ lp["wk"]).reshape(t, nh, hd)
            v = (h @ lp["wv"]).reshape(t, nh, hd)
            scores = jnp.einsum("qnd,knd->nqk", q, k,
                                preferred_element_type=jnp.float32)
            probs = jax.nn.softmax(scores / np.sqrt(hd), axis=-1) \
                .astype(jnp.bfloat16)
            attn = jnp.einsum("nqk,knd->qnd", probs, v).reshape(t, d)
            x = x + attn @ lp["wo"]
            x = x + jax.nn.gelu(norm(x) @ lp["w1"]) @ lp["w2"]
        return (norm(x) @ params["proj"]).astype(jnp.float32)

    def encode(self, img: np.ndarray) -> np.ndarray:
        """Normalized image [S, S, 3] -> [n_patches, llm_hidden]."""
        import jax.numpy as jnp

        return np.asarray(self._fn(self.params, jnp.asarray(img)))


def embed_image(image_bytes: bytes, encoder: VisionEncoder,
                start: int = 0) -> tuple[dict, int]:
    """Image bytes -> (mm_embeds span dict at ``start``, span length)."""
    emb = encoder.encode(decode_image(image_bytes,
                                      size=encoder.spec.image_size))
    return {"start": start, "b": emb.astype(np.float32).tobytes(),
            "dtype": "float32", "shape": list(emb.shape)}, emb.shape[0]


def data_uri_bytes(url: str) -> bytes:
    """Decode a data: URI's payload. Remote http(s) URLs are rejected —
    this deployment model keeps media fetching out of the serving path
    (no egress; clients inline their images)."""
    import base64

    if not url.startswith("data:"):
        raise ValueError(
            "image_url must be a data: URI (base64-inlined); remote "
            "fetching is not supported")
    try:
        _, payload = url.split(",", 1)
        return base64.b64decode(payload)
    except (ValueError, TypeError) as exc:
        raise ValueError(f"malformed data: URI: {exc}") from exc
