"""Token sampling: greedy / temperature / top-k / top-p, fully batched.

Per-slot sampling params live in device arrays so one compiled sampler serves
heterogeneous requests (no recompile per request — XLA static shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(logits: jax.Array, temperature: jax.Array,
                  top_k: jax.Array, top_p: jax.Array, key: jax.Array
                  ) -> jax.Array:
    """logits [B,V] fp32; temperature/top_k/top_p [B]; returns [B] int32.

    temperature <= 0 means greedy for that slot. top_k <= 0 disables top-k;
    top_p >= 1 disables top-p.
    """
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    # Temperature scale (guard zero).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    # top-k: mask logits below the k-th largest.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B,V] descending
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p (nucleus): keep the smallest prefix with cumulative prob >= p.
    sorted_desc2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_desc2, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # Threshold logit: smallest logit still inside the nucleus.
    inside = cum - probs_sorted < top_p[:, None]
    cutoff = jnp.max(jnp.where(inside, jnp.arange(v)[None, :], 0), axis=-1)
    thresh = jnp.take_along_axis(sorted_desc2, cutoff[:, None], axis=1)
    scaled = jnp.where(scaled >= thresh, scaled, -jnp.inf)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
