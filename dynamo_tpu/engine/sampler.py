"""Token sampling: greedy / temperature / top-k / top-p, fully batched.

Per-slot sampling params live in device arrays so one compiled sampler serves
heterogeneous requests (no recompile per request — XLA static shapes).

TPU-conscious design: no full-vocab sorts (a [B,152K] sort costs ~8 ms/step on
v5e — more than the whole 0.5B forward pass). Instead:
- greedy       = argmax                                  (exact)
- plain sample = gumbel-max with per-row noise            (exact)
- top-k/top-p  = lax.top_k(64) prefilter, then gumbel-max over 64 candidates
  (top-k is capped at MAX_TOPK=64; the top-p nucleus is computed within those
  64 — beyond-top-64 tail mass is negligible for real LLM distributions, and
  the reference engines cap similarly for the same reason).

ONE per-row implementation serves every caller: :func:`sample_tokens_per_row`
is the core (an independent PRNG key per row — rows are the unit, so the
[B,S] speculative verify reshapes to [B*S,V] and reuses it unchanged), and
:func:`sample_tokens` is the shared-key wrapper that splits one key across
the batch. Per-row noise is indexed by TOKEN ID (not candidate rank), which
makes a draw depend only on (key, logits): batch composition, candidate
ordering, and bf16 reduction-order jitter between compute paths cannot
remap the noise — the property both seeded reproducibility and the
spec-decode accept rule (sample-the-target, accept iff it equals the draft)
are built on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_TOPK = 64


def sample_tokens_per_row(logits: jax.Array, temperature: jax.Array,
                          top_k: jax.Array, top_p: jax.Array,
                          keys: jax.Array) -> jax.Array:
    """logits [B,V] fp32; temperature/top_k/top_p [B]; keys [B] (one PRNG
    key per row). Returns [B] int32.

    temperature <= 0 means greedy for that slot. top_k <= 0 disables top-k;
    top_p >= 1 disables top-p. A row's draw depends only on its own key and
    logits: other slots' params, seeds, and preemption/replacement cannot
    perturb it (the seeded-request and spec-verify invariant)."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = (top_k > 0) | (top_p < 1.0)
    sampling = temperature > 0

    def do_sample(_):
        safe_t = jnp.where(sampling, temperature, 1.0)
        scaled = logits / safe_t[:, None]
        # ONE noise field per row, indexed by TOKEN ID. The filtered path
        # gathers noise by candidate token id (not candidate rank), so the
        # draw is independent of candidate ordering — bf16 reduction-order
        # jitter between compute paths (fresh vs cached-prefix prefill)
        # reorders near-tied candidates and would otherwise remap the
        # noise and break seeded reproducibility.
        noise_full = jax.vmap(
            lambda k: jax.random.gumbel(k, (v,)))(keys)
        full_sample = jnp.argmax(scaled + noise_full, axis=-1)

        def do_filtered(_):
            # Sample among the top-64 candidates (sorted descending).
            max_k = min(MAX_TOPK, v)
            cand, cand_idx = jax.lax.top_k(scaled, max_k)  # [B,max_k]
            pos = jnp.arange(max_k)[None, :]
            k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, max_k), max_k)
            keep_k = pos < k_eff[:, None]
            probs = jax.nn.softmax(jnp.where(keep_k, cand, -jnp.inf), axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_p = (cum - probs) < top_p[:, None]  # prefix w/ cum >= p
            masked = jnp.where(keep_k & keep_p, cand, -jnp.inf)
            noise = jnp.take_along_axis(noise_full, cand_idx, axis=1)
            choice = jnp.argmax(masked + noise, axis=-1)
            return jnp.take_along_axis(
                cand_idx, choice[:, None], axis=1)[:, 0]

        top_sample = jax.lax.cond(jnp.any(filtered & sampling), do_filtered,
                                  lambda _: full_sample, None)
        return jnp.where(filtered, top_sample,
                         full_sample).astype(jnp.int32)

    # Skip all sampling work when the whole batch is greedy (the common
    # serving default): lax.cond executes one branch on TPU.
    sampled = jax.lax.cond(jnp.any(sampling), do_sample, lambda _: greedy,
                           None)
    return jnp.where(sampling, sampled, greedy)


def sample_tokens(logits: jax.Array, temperature: jax.Array,
                  top_k: jax.Array, top_p: jax.Array, key: jax.Array
                  ) -> jax.Array:
    """Shared-key wrapper over :func:`sample_tokens_per_row`: one key
    split across the batch (the unseeded decode path)."""
    return sample_tokens_per_row(logits, temperature, top_k, top_p,
                                 jax.random.split(key, logits.shape[0]))
