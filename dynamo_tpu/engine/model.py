"""Functional JAX transformer (Llama/Qwen2 family) with a paged KV cache.

Pure-functional, scan-over-layers (O(1) compile time in depth), bfloat16 on
the MXU with fp32 softmax/norm accumulations. Parameters and the KV cache are
sharded over a ("dp", "tp") mesh with XLA inserting the collectives
(all-reduce after attention-out and MLP-down projections) — the tpu-idiomatic
replacement for the reference engines' NCCL tensor parallelism (SURVEY.md
§2.7). RoPE uses HF's rotate-half convention so HF safetensors load directly.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.engine.kv_quant import (gather_pages_folded, scatter_pages,
                                        scatter_tokens)
from dynamo_tpu.engine.quant import QTensor

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Weight application (bf16 or weight-only int8)
# ---------------------------------------------------------------------------

def mm(x: jax.Array, w, pattern: str) -> jax.Array:
    """einsum(x, w) where w may be a QTensor (int8 weight, per-out-channel
    scale): the int8 operand converts to bf16 inside the dot (XLA fuses
    the convert into the operand read — the dequantized matrix is never
    materialized) and the [out] scale multiplies the OUTPUT in f32."""
    if isinstance(w, QTensor):
        y = jnp.einsum(pattern, x, w.q.astype(jnp.bfloat16),
                       preferred_element_type=jnp.bfloat16)
        return (y.astype(jnp.float32) * w.s).astype(jnp.bfloat16)
    return jnp.einsum(pattern, x, w, preferred_element_type=jnp.bfloat16)


def lora_delta(x: jax.Array, ll: dict, ids: jax.Array) -> jax.Array:
    """Gathered batched low-rank correction ``x @ A[ids] @ B[ids]`` —
    the S-LoRA / Punica batched-heterogeneous-adapter step, as two
    gathered einsums so it lives INSIDE the same jit programs as the
    base projections (static shapes: adapter ids are data, not shape).

    x [B, H] or [B, T, H]; ll = one layer's stacks {"a": [S, H, r],
    "b": [S, r, D]}; ids [B] resident slot ids (0 = base model, whose
    stacks are all-zero — the correction is exact zeros and the output
    is bit-identical to the LoRA-free projection). The rank contraction
    accumulates in f32, matching mm()'s numerics discipline."""
    a = jnp.take(ll["a"], ids, axis=0)             # [B, H, r]
    b = jnp.take(ll["b"], ids, axis=0)             # [B, r, D]
    if x.ndim == 2:
        u = jnp.einsum("bh,bhr->br", x, a,
                       preferred_element_type=jnp.float32)
        return jnp.einsum("br,brd->bd", u.astype(jnp.bfloat16), b,
                          preferred_element_type=jnp.bfloat16)
    u = jnp.einsum("bth,bhr->btr", x, a,
                   preferred_element_type=jnp.float32)
    return jnp.einsum("btr,brd->btd", u.astype(jnp.bfloat16), b,
                      preferred_element_type=jnp.bfloat16)


def qkv_lora(q, k, v, h, ll, ids):
    """Apply the wq/wk/wv corrections to freshly-projected q/k/v (h is
    the rms-normed layer input the projections read)."""
    q = q + lora_delta(h, ll["wq"], ids)
    k = k + lora_delta(h, ll["wk"], ids)
    v = v + lora_delta(h, ll["wv"], ids)
    return q, k, v


def embed_lookup(embed, tokens: jax.Array) -> jax.Array:
    """Token-embedding gather; int8 tables gather q rows and scale by the
    per-hidden-channel scale."""
    if isinstance(embed, QTensor):
        rows = embed.q[tokens].astype(jnp.float32) * embed.s[0]
        return rows.astype(jnp.bfloat16)
    return embed[tokens].astype(jnp.bfloat16)


def lm_logits(x: jax.Array, params: Params, spec: ModelSpec) -> jax.Array:
    """Final-hidden -> vocab logits (f32). Tied int8 embeddings contract
    over H, whose scale therefore folds into the activations; untied int8
    heads scale the output columns."""
    if spec.tie_word_embeddings:
        w = params["embed"]
        if isinstance(w, QTensor):
            xs = (x.astype(jnp.float32) * w.s[0]).astype(jnp.bfloat16)
            return jnp.einsum("bh,vh->bv", xs, w.q.astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)
        return jnp.einsum("bh,vh->bv", x, w,
                          preferred_element_type=jnp.float32)
    w = params.get("lm_head")
    if isinstance(w, QTensor):
        y = jnp.einsum("bh,hv->bv", x, w.q.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        return y * w.s
    return jnp.einsum("bh,hv->bv", x, w, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Parameter init + sharding specs
# ---------------------------------------------------------------------------

def param_shapes(spec: ModelSpec) -> dict:
    h, d = spec.hidden_size, spec.head_dim
    nh, nkv, L = spec.num_heads, spec.num_kv_heads, spec.num_layers
    i = spec.intermediate_size
    layers: dict = {
        "input_norm": (L, h),
        "post_attn_norm": (L, h),
        "wq": (L, h, nh * d),
        "wk": (L, h, nkv * d),
        "wv": (L, h, nkv * d),
        "wo": (L, nh * d, h),
    }
    if spec.num_experts:
        E = spec.num_experts
        layers["moe_gate"] = (L, h, E)
        layers["moe_w_gate"] = (L, E, h, i)
        layers["moe_w_up"] = (L, E, h, i)
        layers["moe_w_down"] = (L, E, i, h)
    else:
        layers["w_gate"] = (L, h, i)
        layers["w_up"] = (L, h, i)
        layers["w_down"] = (L, i, h)
    shapes = {
        "embed": (spec.vocab_size, h),
        "final_norm": (h,),
        "layers": layers,
    }
    if spec.qkv_bias:
        shapes["layers"]["bq"] = (L, nh * d)
        shapes["layers"]["bk"] = (L, nkv * d)
        shapes["layers"]["bv"] = (L, nkv * d)
    if not spec.tie_word_embeddings:
        shapes["lm_head"] = (h, spec.vocab_size)
    return shapes


def param_specs(spec: ModelSpec) -> dict:
    """PartitionSpecs: column-parallel qkv/gate/up, row-parallel o/down
    (Megatron layout — XLA adds the psum at row-parallel outputs). The
    stacked LAYER axis shards over "pp" (layer-sharded pipeline axis);
    MoE expert weights shard their EXPERT axis over "tp" (expert
    parallelism: each device computes its resident experts, XLA reduces
    the combine)."""
    layers: dict = {
        "input_norm": P("pp", None),
        "post_attn_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
    }
    if spec.num_experts:
        layers["moe_gate"] = P("pp", None, None)
        layers["moe_w_gate"] = P("pp", "tp", None, None)
        layers["moe_w_up"] = P("pp", "tp", None, None)
        layers["moe_w_down"] = P("pp", "tp", None, None)
    else:
        layers["w_gate"] = P("pp", None, "tp")
        layers["w_up"] = P("pp", None, "tp")
        layers["w_down"] = P("pp", "tp", None)
    specs = {
        "embed": P(None, "tp"),
        "final_norm": P(None),
        "layers": layers,
    }
    if spec.qkv_bias:
        specs["layers"]["bq"] = P("pp", "tp")
        specs["layers"]["bk"] = P("pp", "tp")
        specs["layers"]["bv"] = P("pp", "tp")
    if not spec.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    if spec.quant == "int8":
        # QTensor leaves mirror the weight spec; the scale keeps the
        # contraction axis (-2, size 1 in the scale) UNSHARDED — a 1-sized
        # axis can't shard over tp (wo/w_down are row-parallel there).
        from dynamo_tpu.engine.quant import QUANT_LAYER_KEYS

        def scale_spec(p: P) -> P:
            parts = list(p)
            parts[-2] = None
            return P(*parts)

        for key in QUANT_LAYER_KEYS:
            if key in specs["layers"]:
                p = specs["layers"][key]
                specs["layers"][key] = QTensor(q=p, s=scale_spec(p))
        specs["embed"] = QTensor(q=P(None, "tp"), s=P(None, "tp"))
        if not spec.tie_word_embeddings:
            specs["lm_head"] = QTensor(q=P(None, "tp"), s=P(None, "tp"))
    return specs


def ffn_block(h2: jax.Array, lp: dict, spec: ModelSpec, ll: dict | None = None,
              ids: jax.Array | None = None) -> jax.Array:
    """Feed-forward over normalized hidden states [..., H]: dense SwiGLU,
    or Mixtral-style top-k MoE when spec.num_experts > 0.

    MoE formulation (TPU-first): router top-k softmax gating; every
    RESIDENT expert computes the whole token batch and the combine
    contracts over the expert axis — with experts sharded over "tp" each
    device runs E/tp experts and XLA inserts the psum, i.e. expert
    parallelism without a dynamic all-to-all (serving batches are small;
    capacity-based dispatch kernels are a future optimization)."""
    if not spec.num_experts:
        # Dense-MLP LoRA targets (gathered per-row deltas; MoE expert
        # weights are not adapter targets — attention-only there, so the
        # stacks simply lack the MLP keys).
        mlp_lora = ll is not None and "w_gate" in ll
        gate = mm(h2, lp["w_gate"], "...h,hi->...i")
        up = mm(h2, lp["w_up"], "...h,hi->...i")
        if mlp_lora:
            gate = gate + lora_delta(h2, ll["w_gate"], ids)
            up = up + lora_delta(h2, ll["w_up"], ids)
        ff = jax.nn.silu(gate.astype(jnp.float32)).astype(jnp.bfloat16) * up
        down = mm(ff, lp["w_down"], "...i,ih->...h")
        if mlp_lora:
            down = down + lora_delta(ff, ll["w_down"], ids)
        return down
    orig = h2.shape
    x = h2.reshape(-1, orig[-1])                       # [T, H]
    router = jnp.einsum("th,he->te", x, lp["moe_gate"],
                        preferred_element_type=jnp.float32)
    top_v, top_i = jax.lax.top_k(router, spec.num_experts_per_tok)
    gates = jax.nn.softmax(top_v, axis=-1)             # Mixtral: over top-k
    one_hot = jax.nn.one_hot(top_i, spec.num_experts, dtype=jnp.float32)
    w_te = jnp.einsum("tk,tke->te", gates, one_hot)    # [T, E] sparse-ish
    gate = mm(x, lp["moe_w_gate"], "th,ehi->eti")
    up = mm(x, lp["moe_w_up"], "th,ehi->eti")
    ff = jax.nn.silu(gate.astype(jnp.float32)).astype(jnp.bfloat16) * up
    wd = lp["moe_w_down"]
    if isinstance(wd, QTensor):
        down = (jnp.einsum("eti,eih->eth", ff, wd.q.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32) * wd.s)
    else:
        down = jnp.einsum("eti,eih->eth", ff, wd,
                          preferred_element_type=jnp.float32)
    out = jnp.einsum("eth,te->th", down, w_te)
    return out.astype(jnp.bfloat16).reshape(orig)


def init_params(spec: ModelSpec, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random init (bench/smoke). Real weights come from the safetensors
    loader (dynamo_tpu.engine.weights)."""
    shapes = param_shapes(spec)
    leaves, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(key, len(leaves))

    def init_one(shape, k):
        if len(shape) == 1 or shape[-1] == 1:
            return jnp.ones(shape, dtype)  # norm scales
        fan_in = shape[-2] if len(shape) > 1 else shape[-1]
        return (jax.random.normal(k, shape, dtype)
                * (1.0 / jnp.sqrt(fan_in)).astype(dtype))

    inited = [init_one(s, k) for s, k in zip(leaves, keys)]
    params = jax.tree.unflatten(treedef, inited)
    # Norm scales must be ones.
    params["final_norm"] = jnp.ones(shapes["final_norm"], dtype)
    params["layers"]["input_norm"] = jnp.ones(
        shapes["layers"]["input_norm"], dtype)
    params["layers"]["post_attn_norm"] = jnp.ones(
        shapes["layers"]["post_attn_norm"], dtype)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope_tables(positions: jax.Array, head_dim: int, theta: float
                ) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for HF rotate-half RoPE; positions [...]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    return cos, sin


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [..., heads, head_dim]; cos/sin [..., half] (broadcast over heads)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos - xf2 * sin
    out2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def ring_causal_attention(mesh, q: jax.Array, k: jax.Array, v: jax.Array,
                          q_positions: jax.Array, kv_len_mask: jax.Array,
                          q_per_kv: int) -> jax.Array:
    """Ring attention over the "sp" mesh axis (blockwise causal prefill
    attention with online softmax; Liu et al.'s ring attention shape,
    lax-level).

    The GSPMD sp path all-gathers the full K/V onto every shard before
    the quadratic scores — O(s) memory per device in sequence length.
    Here each sp shard keeps its sequence block resident and the K/V
    blocks ROTATE around the ring (lax.ppermute neighbor exchange over
    ICI), with a running (max, sum, acc) online softmax — peak K/V
    memory is one block, and each hop's transfer overlaps the previous
    block's matmul in XLA's schedule. Queries never move (they are the
    larger tensor with GQA).

    q [B,S,Nh,D], k/v [B,S,Nkv,D], q_positions [B,S] absolute,
    kv_len_mask [B,S] — sequence-sharded over "sp" AND head-sharded over
    "tp" (both axes stay manual in the shard_map, so tp keeps its
    head-parallel split instead of being all-gathered; the head-major
    [nkv, g] layout keeps each kv group's q heads on the group's tp
    shard, so GQA grouping is shard-local). Causality rides the ABSOLUTE
    positions travelling with each block, so no step/offset bookkeeping
    is needed. The ring loop is UNROLLED over the (static, small) shard
    count: the last block skips the rotation — a fori_loop would pay one
    dead full-K/V neighbor hop per layer. fp32 accumulation, bf16 matmul
    operands — same numerics recipe as the dense path. The reference has
    no sequence parallelism at all (SURVEY §2.7); this is a
    beyond-parity capability."""
    try:
        from jax import shard_map  # jax >= 0.8 home
    except ImportError:
        from jax.experimental.shard_map import shard_map

    n_shards = mesh.shape["sp"]
    scale = 1.0 / math.sqrt(q.shape[-1])

    def local(q_blk, k_blk, v_blk, qpos_blk, kmask_blk):
        b, sq, nh, d = q_blk.shape  # nh, nkv are per-tp-shard counts here
        nkv = k_blk.shape[2]
        qg = q_blk.reshape(b, sq, nkv, q_per_kv, d)
        m = jnp.full((b, nkv, q_per_kv, sq), -1e30, jnp.float32)
        l = jnp.zeros((b, nkv, q_per_kv, sq), jnp.float32)
        acc = jnp.zeros((b, nkv, q_per_kv, sq, d), jnp.float32)
        k_c, v_c = k_blk, v_blk
        kpos, kmask = qpos_blk, kmask_blk
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        for t in range(n_shards):
            if t > 0:  # rotate-before-compute: no dead final hop
                k_c = jax.lax.ppermute(k_c, "sp", perm)
                v_c = jax.lax.ppermute(v_c, "sp", perm)
                kpos = jax.lax.ppermute(kpos, "sp", perm)
                kmask = jax.lax.ppermute(kmask, "sp", perm)
            s = jnp.einsum("bqngd,bknd->bngqk", qg, k_c,
                           preferred_element_type=jnp.float32) * scale
            ok = ((qpos_blk[:, None, None, :, None]
                   >= kpos[:, None, None, None, :])
                  & kmask[:, None, None, None, :])
            s = jnp.where(ok, s, -1e30)
            m_new = jnp.maximum(m, s.max(axis=-1))
            # where() rather than bare exp: an all-masked block would
            # otherwise yield exp(-1e30 - (-1e30)) = 1 per masked key.
            p = jnp.where(ok, jnp.exp(s - m_new[..., None]), 0.0)
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(axis=-1)
            acc = (acc * corr[..., None]
                   + jnp.einsum("bngqk,bknd->bngqd",
                                p.astype(jnp.bfloat16), v_c
                                ).astype(jnp.float32))
            m = m_new
        out = acc / jnp.maximum(l, 1e-9)[..., None]
        # [B,Nkv,G,sq,D] -> [B,sq,Nh,D]
        return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, nh, d) \
            .astype(q_blk.dtype)

    seq_heads = P(None, "sp", "tp", None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(seq_heads, seq_heads, seq_heads,
                  P(None, "sp"), P(None, "sp")),
        out_specs=seq_heads)(q, k, v, q_positions, kv_len_mask)


def dense_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           q_positions: jax.Array, kv_len_mask: jax.Array,
                           q_per_kv: int) -> jax.Array:
    """Prefill attention over freshly-computed K/V.

    q [B,S,Nh,D], k/v [B,S,Nkv,D], q_positions [B,S] (absolute), kv_len_mask
    [B,S] bool (valid kv slots). Causal by position. fp32 accumulation.
    GQA handled by grouping q heads (no materialized repeat).
    """
    b, s, nh, d = q.shape
    nkv = k.shape[2]
    qg = q.reshape(b, s, nkv, q_per_kv, d)
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    causal = (q_positions[:, None, None, :, None]
              >= q_positions[:, None, None, None, :])
    valid = kv_len_mask[:, None, None, None, :]
    scores = jnp.where(causal & valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, v)
    return out.reshape(b, s, nh, d)


def paged_decode_attention_xla(q: jax.Array, k_cache: jax.Array,
                               v_cache: jax.Array, layer: jax.Array,
                               page_table: jax.Array, hist_lens: jax.Array,
                               k_self: jax.Array, v_self: jax.Array,
                               q_per_kv: int) -> jax.Array:
    """Gather-based decode attention over the FULL stacked cache.

    q [B,Nh,D]; k_cache/v_cache [L,Nkv,P,page,D]; layer: scalar layer index;
    page_table [B,maxP]; hist_lens [B] = tokens already IN the cache (the
    new token travels as k_self/v_self [B,Nkv,D] — its cache write is
    deferred so the whole forward needs only ONE scatter; see
    decode_forward). The layer index is folded into the gather itself —
    never slice the cache (a dynamic-slice copy of cache/L per layer is the
    difference between 1.5 ms and 50 ms steps at multi-GB pools).

    This is the window attention with zero in-window columns."""
    b = q.shape[0]
    nkv, d = k_cache.shape[1], k_cache.shape[4]
    empty = jnp.zeros((nkv, b, 0, d), k_cache.dtype)
    return paged_window_attention_xla(
        q, k_cache, v_cache, layer, page_table, hist_lens, empty, empty,
        jnp.asarray(0, jnp.int32), k_self, v_self, q_per_kv)


def paged_window_attention_xla(q: jax.Array, k_cache: jax.Array,
                               v_cache: jax.Array, layer: jax.Array,
                               page_table: jax.Array, hist_lens: jax.Array,
                               k_win: jax.Array, v_win: jax.Array,
                               m: jax.Array, k_self: jax.Array,
                               v_self: jax.Array, q_per_kv: int) -> jax.Array:
    """Decode attention for step ``m`` of an M-step window.

    Keys/values come from three places: pages already in the cache
    (hist_lens tokens, read via a layer-folded gather), the in-window
    buffer k_win/v_win [Nkv,B,M,D] holding this window's previous steps
    (cols j < m valid), and the current token (k_self/v_self [B,Nkv,D]).
    The cache itself is read-only here — the window's writes are committed
    by ONE scatter after the step scan, which is what lets XLA run the
    whole window without copying the multi-GB pool (see runner._get_window).
    """
    b, nh, d = q.shape
    nkv, page = k_cache.shape[1], k_cache.shape[3]
    maxp = page_table.shape[1]
    M = k_win.shape[2]
    # Layer+head-folded gather straight into the dot's [Nkv,B,L,D]
    # operand layout (no transposed relayout of the gathered history);
    # dequantizes int8 pools inside the gather expression.
    k_all = gather_pages_folded(k_cache, layer, page_table)
    v_all = gather_pages_folded(v_cache, layer, page_table)
    qg = q.reshape(b, nkv, q_per_kv, d)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    s_hist = jnp.einsum("bngd,nbld->bngl", qg, k_all,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(maxp * page)[None, :]
    s_hist = jnp.where((pos < hist_lens[:, None])[:, None, None, :],
                       s_hist, -1e30)
    s_win = jnp.einsum("bngd,nbjd->bngj", qg, k_win,
                       preferred_element_type=jnp.float32) * scale
    win_valid = (jnp.arange(M)[None, :] < m)[:, None, None, :]
    s_win = jnp.where(jnp.broadcast_to(win_valid, s_win.shape), s_win, -1e30)
    s_self = jnp.einsum("bngd,bnd->bng", qg, k_self,
                        preferred_element_type=jnp.float32)[..., None] * scale
    full = jnp.concatenate([s_hist, s_win, s_self], axis=-1)
    probs = jax.nn.softmax(full, axis=-1)
    p_hist = probs[..., :maxp * page].astype(q.dtype)
    p_win = probs[..., maxp * page:-1].astype(q.dtype)
    p_self = probs[..., -1]
    out = (jnp.einsum("bngl,nbld->bngd", p_hist, v_all)
           + jnp.einsum("bngj,nbjd->bngd", p_win, v_win)
           + p_self[..., None].astype(q.dtype) * v_self[:, :, None, :])
    return out.reshape(b, nh, d)


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------

def _split_heads(x, n, d):
    return x.reshape(*x.shape[:-1], n, d)


def prefill_forward(params: Params, spec: ModelSpec,
                    k_cache: jax.Array, v_cache: jax.Array,
                    tokens: jax.Array, positions: jax.Array,
                    page_table: jax.Array, seq_lens: jax.Array,
                    sp_shard: bool = False, ring_mesh=None,
                    x_embeds: jax.Array | None = None,
                    embeds_mask: jax.Array | None = None,
                    lora: dict | None = None,
                    adapter_ids: jax.Array | None = None,
                    ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Process prompt chunks and write K/V into pages.

    tokens/positions [B,S] (S = bucket, multiple of page_size), page_table
    [B, S//page_size] (pages covering THIS chunk), seq_lens [B] (valid token
    counts). With sp_shard (requires tracing under the runner's mesh), the
    SEQUENCE axis of activations is sharded over the "sp" mesh axis —
    all-to-all context parallelism: queries stay sequence-sharded, XLA
    gathers K/V, and the quadratic score tensor is sp-sharded, which is
    what lets long-context prefill fit (SURVEY §5.7; ring attention is the
    bandwidth optimization path). Returns (last_token_logits [B,V],
    k_cache, v_cache).
    """
    b, s = tokens.shape
    d = spec.head_dim
    page = k_cache.shape[3]
    x = embed_lookup(params["embed"], tokens)  # [B,S,H]
    if x_embeds is not None:
        # Multimodal spans: encoder-produced embeddings replace the token
        # table's rows wherever the mask is set (the placeholder ids
        # under the span never reach the model).
        x = jnp.where(embeds_mask[..., None], x_embeds.astype(x.dtype), x)
    if sp_shard:
        x = jax.lax.with_sharding_constraint(x, P(None, "sp", None))
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    valid = jnp.arange(s)[None, :] < seq_lens[:, None]

    def layer_fn(x, scan_in):
        lp, ll = scan_in if lora is not None else (scan_in, None)
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bsh,hd->bsd")
        k = mm(h, lp["wk"], "bsh,hd->bsd")
        v = mm(h, lp["wv"], "bsh,hd->bsd")
        if ll is not None:
            q, k, v = qkv_lora(q, k, v, h, ll, adapter_ids)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, spec.num_kv_heads, d)
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if ring_mesh is not None:
            attn = ring_causal_attention(ring_mesh, q, k, v, positions,
                                         valid, spec.q_per_kv)
        else:
            attn = dense_causal_attention(q, k, v, positions, valid,
                                          spec.q_per_kv)
        attn = attn.reshape(b, s, -1)
        proj = mm(attn, lp["wo"], "bsd,dh->bsh")
        if ll is not None:
            proj = proj + lora_delta(attn, ll["wo"], adapter_ids)
        x = x + proj
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec, ll, adapter_ids)
        return x, (k, v)

    # Cache writes are deferred out of the scan (ys are fresh allocations —
    # carrying the caches through would rewrite the whole pool per call).
    xs = (params["layers"], lora) if lora is not None else params["layers"]
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    # k_new [L,B,S,Nkv,D] -> page blocks [L,Nkv,B*S/page,page,D]; one
    # in-place scatter per cache covers every layer.
    L = spec.num_layers
    nkv = spec.num_kv_heads
    k_blocks = (k_new.reshape(L, b * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    v_blocks = (v_new.reshape(L, b * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    flat_pages = page_table.reshape(-1)
    # scatter_pages quantizes int8 pools in the same fused commit.
    k_cache = scatter_pages(k_cache, k_blocks, flat_pages)
    v_cache = scatter_pages(v_cache, v_blocks, flat_pages)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    # Last valid token per sequence.
    last_idx = jnp.maximum(seq_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = lm_logits(x_last, params, spec)
    return logits, k_cache, v_cache


def prefill_forward_pipelined(params: Params, spec: ModelSpec,
                              k_cache: jax.Array, v_cache: jax.Array,
                              tokens: jax.Array, positions: jax.Array,
                              page_table: jax.Array, seq_lens: jax.Array,
                              n_stages: int
                              ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """MICROBATCHED pipeline-parallel prefill: GPipe-style fill/drain over
    the "pp" mesh axis, expressed in pure GSPMD (no shard_map).

    The layer-sharded pp path (prefill_forward with P("pp") on the layer
    axis) distributes memory but serializes stages — each stage idles
    while the single batch traverses the other stages' layers. Here the
    batch's ROWS split into ``n_stages`` microbatches that flow through
    the stages concurrently:

    - weights reshape [L, ...] -> [S, L/S, ...] (the pp-sharded L axis
      becomes the stage axis — layout-preserving, one shard per stage);
    - activations live in a stage buffer x[S, mb, s, H] sharded
      P("pp", ...): tick t runs jax.vmap(stage_forward) over the stage
      axis, so GSPMD executes every stage's L/S layers IN PARALLEL on its
      own devices (this is the overlap);
    - between ticks the buffer shifts one stage (jnp.roll on the
      pp-sharded axis lowers to a collective-permute over ICI — the
      artifact to look for in the compiled HLO), stage 0 ingests the next
      microbatch's embeddings, and stage S-1's output drains into the
      result buffer;
    - each tick's fresh K/V lands in a [G, S, ...] buffer indexed by
      (microbatch, stage) with out-of-range (bubble) ticks clamped to a
      discard row; ONE page scatter at the end commits everything, same
      as prefill_forward.

    G = S microbatches -> G+S-1 ticks, bubble fraction (S-1)/(2S-1).
    Rows must divide evenly by n_stages (the runner pads the batch).
    The reference delegates PP to its engines (trtllm main.py:162
    pipeline_parallel_size); this repo IS the engine, so the capability
    is native (round-3 VERDICT missing #4).
    """
    B, s = tokens.shape
    S = n_stages
    G = S  # microbatches
    assert B % G == 0, (B, G)
    mb = B // G
    d = spec.head_dim
    page = k_cache.shape[3]
    L = spec.num_layers
    Ls = L // S
    nkv = spec.num_kv_heads

    # Weights: [L, ...] -> [S, L/S, ...]; the pp-sharded L axis becomes
    # the stage axis (explicit constraint keeps GSPMD from re-sharding).
    def stage_weights(w):
        out = w.reshape(S, Ls, *w.shape[1:])
        return jax.lax.with_sharding_constraint(
            out, P("pp", *([None] * (out.ndim - 1))))

    w_stages = jax.tree.map(stage_weights, params["layers"])

    # Per-microbatch inputs, precomputed: [G, mb, s, ...].
    emb = embed_lookup(params["embed"], tokens).reshape(G, mb, s, -1)
    pos_g = positions.reshape(G, mb, s)
    valid_g = (jnp.arange(s)[None, :]
               < seq_lens[:, None]).reshape(G, mb, s)

    def stage_forward(w, x, pos, valid):
        """L/S layers of ONE stage on one microbatch (the inner loop of
        prefill_forward, minus embed/head)."""
        cos, sin = rope_tables(pos, d, spec.rope_theta)

        def layer_fn(x, lp):
            h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
            q = mm(h, lp["wq"], "bsh,hd->bsd")
            k = mm(h, lp["wk"], "bsh,hd->bsd")
            v = mm(h, lp["wv"], "bsh,hd->bsd")
            if spec.qkv_bias:
                q = q + lp["bq"]
                k = k + lp["bk"]
                v = v + lp["bv"]
            q = apply_rope(_split_heads(q, spec.num_heads, d), cos, sin)
            k = apply_rope(_split_heads(k, nkv, d), cos, sin)
            v = _split_heads(v, nkv, d)
            attn = dense_causal_attention(q, k, v, pos, valid,
                                          spec.q_per_kv)
            x = x + mm(attn.reshape(mb, s, -1), lp["wo"], "bsd,dh->bsh")
            h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
            x = x + ffn_block(h2, lp, spec)
            return x, (k, v)

        x, (k_new, v_new) = jax.lax.scan(layer_fn, x, w)
        return x, k_new, v_new  # k/v: [L/S, mb, s, nkv, d]

    x0 = jnp.zeros((S, mb, s, emb.shape[-1]), jnp.bfloat16)
    x0 = jax.lax.with_sharding_constraint(x0, P("pp", None, None, None))
    pos0 = jnp.zeros((S, mb, s), positions.dtype)
    val0 = jnp.zeros((S, mb, s), bool)
    # (microbatch, stage) K/V accumulator + a discard row at index G for
    # bubble-tick outputs.
    kbuf0 = jnp.zeros((G + 1, S, Ls, mb, s, nkv, d), k_cache.dtype)
    vbuf0 = jnp.zeros_like(kbuf0)
    xout0 = jnp.zeros((G + 1, mb, s, emb.shape[-1]), jnp.bfloat16)

    def tick(carry, t):
        x_st, pos_st, val_st, kbuf, vbuf, xout = carry
        # Ingest: stage 0 takes microbatch t (clamped; bubble ticks feed
        # stage 0 stale data whose outputs are discarded below).
        g_in = jnp.clip(t, 0, G - 1)
        x_st = x_st.at[0].set(emb[g_in])
        pos_st = pos_st.at[0].set(pos_g[g_in])
        val_st = val_st.at[0].set(valid_g[g_in])
        x_new, k_new, v_new = jax.vmap(stage_forward)(
            w_stages, x_st, pos_st, val_st)
        # Stage s just processed microbatch t - s: scatter its K/V into
        # the (g, s) buffer; bubble outputs land on the discard row G.
        g_of_stage = t - jnp.arange(S)
        g_idx = jnp.where((g_of_stage >= 0) & (g_of_stage < G),
                          g_of_stage, G)
        kbuf = kbuf.at[g_idx, jnp.arange(S)].set(k_new)
        vbuf = vbuf.at[g_idx, jnp.arange(S)].set(v_new)
        # Drain: stage S-1's output is microbatch t-(S-1), complete.
        g_out = t - (S - 1)
        xout = xout.at[jnp.where((g_out >= 0) & (g_out < G), g_out, G)] \
            .set(x_new[S - 1])
        # Shift one stage forward (collective-permute over "pp").
        x_st = jax.lax.with_sharding_constraint(
            jnp.roll(x_new, 1, axis=0), P("pp", None, None, None))
        pos_st = jnp.roll(pos_st, 1, axis=0)
        val_st = jnp.roll(val_st, 1, axis=0)
        return (x_st, pos_st, val_st, kbuf, vbuf, xout), ()

    (_, _, _, kbuf, vbuf, xout), _ = jax.lax.scan(
        tick, (x0, pos0, val0, kbuf0, vbuf0, xout0),
        jnp.arange(G + S - 1))

    # [G, S, L/S, mb, s, nkv, d] -> [L, B*s/page, page, nkv, d] blocks.
    k_new = (kbuf[:G].transpose(1, 2, 0, 3, 4, 5, 6)
             .reshape(L, B, s, nkv, d))
    v_new = (vbuf[:G].transpose(1, 2, 0, 3, 4, 5, 6)
             .reshape(L, B, s, nkv, d))
    k_blocks = (k_new.reshape(L, B * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    v_blocks = (v_new.reshape(L, B * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    flat_pages = page_table.reshape(-1)
    # scatter_pages quantizes int8 pools in the same fused commit.
    k_cache = scatter_pages(k_cache, k_blocks, flat_pages)
    v_cache = scatter_pages(v_cache, v_blocks, flat_pages)

    x = xout[:G].reshape(B, s, -1)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    last_idx = jnp.maximum(seq_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = lm_logits(x_last, params, spec)
    return logits, k_cache, v_cache


def decode_forward(params: Params, spec: ModelSpec,
                   k_cache: jax.Array, v_cache: jax.Array,
                   tokens: jax.Array, positions: jax.Array,
                   page_table: jax.Array, seq_lens: jax.Array,
                   attention_impl=None, write_mask: jax.Array | None = None,
                   lora: dict | None = None,
                   adapter_ids: jax.Array | None = None,
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for the whole slot batch.

    tokens [B], positions [B] (absolute position of the new token), page_table
    [B, maxP], seq_lens [B] (lengths INCLUDING the new token). write_mask [B]
    bool (optional): rows with False scatter their K/V to the reserved
    scratch page 0 instead of their own pages (used by the window loop to
    freeze slots that hit page capacity mid-window). Returns
    (logits [B,V], k_cache, v_cache).
    """
    b = tokens.shape[0]
    d = spec.head_dim
    page = k_cache.shape[3]
    x = embed_lookup(params["embed"], tokens)  # [B,H]
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    # Target page slot for the new token.
    page_idx = positions // page
    page_off = positions % page
    dest_page = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]
    if write_mask is not None:
        dest_page = jnp.where(write_mask, dest_page, 0)
        page_off = jnp.where(write_mask, page_off, 0)
    attn_fn = attention_impl or paged_decode_attention_xla
    # The new token's K/V is NOT written inside the layer loop: attention
    # takes it as an explicit self column (hist_lens = cache-resident
    # length) and one batched scatter below writes all layers at once. The
    # caches therefore never ride the scan as stacked ys — scan ys are
    # freshly allocated each call, which silently rewrote the ENTIRE pool
    # per decode step (50 ms/step at a 3 GB pool vs ~1.5 ms now).
    hist_lens = jnp.maximum(seq_lens - 1, 0)
    L = spec.num_layers

    def layer_fn(x, scan_in):
        if lora is not None:
            lp, layer, ll = scan_in
        else:
            (lp, layer), ll = scan_in, None
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bh,hd->bd")
        k = mm(h, lp["wk"], "bh,hd->bd")
        v = mm(h, lp["wv"], "bh,hd->bd")
        if ll is not None:
            q, k, v = qkv_lora(q, k, v, h, ll, adapter_ids)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)       # [B,Nh,D]
        k = _split_heads(k, spec.num_kv_heads, d)    # [B,Nkv,D]
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attn_fn(q, k_cache, v_cache, layer, page_table, hist_lens,
                       k, v, spec.q_per_kv)  # [B,Nh,D]
        attn = attn.reshape(b, -1)
        proj = mm(attn, lp["wo"], "bd,dh->bh")
        if ll is not None:
            proj = proj + lora_delta(attn, ll["wo"], adapter_ids)
        x = x + proj
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec, ll, adapter_ids)
        return x, (k, v)

    xs = ((params["layers"], jnp.arange(L), lora) if lora is not None
          else (params["layers"], jnp.arange(L)))
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    # One in-place scatter: [L,Nkv,B,D] at (dest_page[b], page_off[b]).
    k_cache = scatter_tokens(k_cache, k_new.transpose(0, 2, 1, 3),
                             dest_page, page_off)
    v_cache = scatter_tokens(v_cache, v_new.transpose(0, 2, 1, 3),
                             dest_page, page_off)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    logits = lm_logits(x, params, spec)
    return logits, k_cache, v_cache


def decode_window_multi_step(params: Params, spec: ModelSpec,
                             k_cache: jax.Array, v_cache: jax.Array,
                             k_buf: jax.Array, v_buf: jax.Array,
                             wlen: jax.Array, tokens: jax.Array,
                             positions: jax.Array, page_table: jax.Array,
                             hist_lens: jax.Array,
                             lora: dict | None = None,
                             adapter_ids: jax.Array | None = None
                             ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative-verification step INSIDE a window: S tokens per slot
    (the chained token + S-1 n-gram drafts) forwarded TOGETHER — one
    weight read verifies S positions, which is the whole point of
    speculative decoding on an HBM-bound decode (SURVEY §5.7; reference
    delegates spec decode to its engines, protocols.rs:32-56 stats).

    tokens/positions [B,S]; wlen [B] = valid columns already committed to
    the in-window buffer k_buf/v_buf [L,Nkv,B,W,D]; hist_lens [B] =
    cache-resident tokens. Attention per query j: paged history +
    window-buffer cols < wlen + in-block causal (cols <= j).
    Returns (logits [B,S,V], k_new, v_new [L,B,S,Nkv,D])."""
    b, s = tokens.shape
    d = spec.head_dim
    nkv = spec.num_kv_heads
    page = k_cache.shape[3]
    maxp = page_table.shape[1]
    W = k_buf.shape[3]
    x = embed_lookup(params["embed"], tokens)          # [B,S,H]
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    L = spec.num_layers

    def layer_fn(x, scan_in):
        if lora is not None:
            lp, layer, kb_l, vb_l, ll = scan_in        # kb_l [Nkv,B,W,D]
        else:
            (lp, layer, kb_l, vb_l), ll = scan_in, None
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bsh,hd->bsd")
        k = mm(h, lp["wk"], "bsh,hd->bsd")
        v = mm(h, lp["wv"], "bsh,hd->bsd")
        if ll is not None:
            q, k, v = qkv_lora(q, k, v, h, ll, adapter_ids)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)         # [B,S,Nh,D]
        k = _split_heads(k, nkv, d)                    # [B,S,Nkv,D]
        v = _split_heads(v, nkv, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        qg = q.reshape(b, s, nkv, spec.q_per_kv, d)
        # Paged history: the same layer+head-folded fused gather as the
        # single-token step — the [B,S] verify reads the bucketed page
        # table once per layer into the dot's [Nkv,B,L,D] layout, with
        # no materialized per-position (or per-head-transpose) copies.
        k_all = gather_pages_folded(k_cache, layer, page_table)
        v_all = gather_pages_folded(v_cache, layer, page_table)
        s_hist = jnp.einsum("bsngd,nbld->bnsgl", qg, k_all,
                            preferred_element_type=jnp.float32) * scale
        lpos = jnp.arange(maxp * page)[None, :]
        s_hist = jnp.where(
            (lpos < hist_lens[:, None])[:, None, None, None, :],
            s_hist, -1e30)
        # This window's committed columns (< wlen per slot).
        s_win = jnp.einsum("bsngd,nbjd->bnsgj", qg, kb_l,
                           preferred_element_type=jnp.float32) * scale
        wvalid = (jnp.arange(W)[None, :]
                  < wlen[:, None])[:, None, None, None, :]
        s_win = jnp.where(jnp.broadcast_to(wvalid, s_win.shape),
                          s_win, -1e30)
        # In-block causal among the S verify tokens.
        s_blk = jnp.einsum("bsngd,btnd->bnsgt", qg, k,
                           preferred_element_type=jnp.float32) * scale
        causal = (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])
        s_blk = jnp.where(causal[None, None, :, None, :], s_blk, -1e30)
        full = jnp.concatenate([s_hist, s_win, s_blk], axis=-1)
        probs = jax.nn.softmax(full, axis=-1)
        p_hist = probs[..., :maxp * page].astype(q.dtype)
        p_win = probs[..., maxp * page:maxp * page + W].astype(q.dtype)
        p_blk = probs[..., maxp * page + W:].astype(q.dtype)
        out = (jnp.einsum("bnsgl,nbld->bsngd", p_hist, v_all)
               + jnp.einsum("bnsgj,nbjd->bsngd", p_win, vb_l)
               + jnp.einsum("bnsgt,btnd->bsngd", p_blk, v))
        attn = out.reshape(b, s, -1)
        proj = mm(attn, lp["wo"], "bsd,dh->bsh")
        if ll is not None:
            proj = proj + lora_delta(attn, ll["wo"], adapter_ids)
        x = x + proj
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec, ll, adapter_ids)
        return x, (k, v)

    xs = ((params["layers"], jnp.arange(L), k_buf, v_buf, lora)
          if lora is not None
          else (params["layers"], jnp.arange(L), k_buf, v_buf))
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    logits = lm_logits(x.reshape(b * s, -1), params, spec)
    return logits.reshape(b, s, -1), k_new, v_new


def embed_forward(params: Params, spec: ModelSpec, tokens: jax.Array,
                  seq_lens: jax.Array, pooling: str = "last"
                  ) -> jax.Array:
    """Embedding forward: full transformer pass, pooled final hidden
    states (no KV cache — embeddings are single-shot). tokens [B,S]
    (padded), seq_lens [B]. pooling: "last" (final valid token) or
    "mean" (masked mean). Returns L2-normalized [B,H] float32 — the
    engine side of /v1/embeddings (reference embeddings path,
    lib/llm/src/protocols/openai/embeddings*)."""
    b, s = tokens.shape
    d = spec.head_dim
    x = embed_lookup(params["embed"], tokens)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    valid = jnp.arange(s)[None, :] < seq_lens[:, None]

    def layer_fn(x, lp):
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bsh,hd->bsd")
        k = mm(h, lp["wk"], "bsh,hd->bsd")
        v = mm(h, lp["wv"], "bsh,hd->bsd")
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, spec.num_kv_heads, d)
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = dense_causal_attention(q, k, v, positions, valid,
                                      spec.q_per_kv)
        x = x + mm(attn.reshape(b, s, -1), lp["wo"], "bsd,dh->bsh")
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec)
        return x, ()

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps).astype(
        jnp.float32)
    if pooling == "mean":
        m = valid[..., None].astype(jnp.float32)
        pooled = (x * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    else:
        last = jnp.maximum(seq_lens - 1, 0)
        pooled = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


def decode_window_step(params: Params, spec: ModelSpec,
                       k_cache: jax.Array, v_cache: jax.Array,
                       k_buf: jax.Array, v_buf: jax.Array, m: jax.Array,
                       tokens: jax.Array, positions: jax.Array,
                       page_table: jax.Array, hist_lens: jax.Array,
                       attention_impl=None, lora: dict | None = None,
                       adapter_ids: jax.Array | None = None
                       ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step INSIDE an M-step window: the caches are read-only
    (gathered), this window's earlier tokens come from k_buf/v_buf
    [L,Nkv,B,M,D], and the step's fresh K/V is returned ([L,B,Nkv,D]) for
    the caller to append to the buffer — no cache writes here at all.

    hist_lens [B]: tokens cache-resident BEFORE the window (fixed across
    the window). Returns (logits [B,V], k_new, v_new).
    """
    b = tokens.shape[0]
    d = spec.head_dim
    x = embed_lookup(params["embed"], tokens)
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    attn_fn = attention_impl or paged_window_attention_xla
    L = spec.num_layers

    def layer_fn(x, scan_in):
        if lora is not None:
            lp, layer, kb_l, vb_l, ll = scan_in
        else:
            (lp, layer, kb_l, vb_l), ll = scan_in, None
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bh,hd->bd")
        k = mm(h, lp["wk"], "bh,hd->bd")
        v = mm(h, lp["wv"], "bh,hd->bd")
        if ll is not None:
            q, k, v = qkv_lora(q, k, v, h, ll, adapter_ids)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, spec.num_kv_heads, d)
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attn_fn(q, k_cache, v_cache, layer, page_table, hist_lens,
                       kb_l, vb_l, m, k, v, spec.q_per_kv)
        attn = attn.reshape(b, -1)
        proj = mm(attn, lp["wo"], "bd,dh->bh")
        if ll is not None:
            proj = proj + lora_delta(attn, ll["wo"], adapter_ids)
        x = x + proj
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec, ll, adapter_ids)
        return x, (k, v)

    xs = ((params["layers"], jnp.arange(L), k_buf, v_buf, lora)
          if lora is not None
          else (params["layers"], jnp.arange(L), k_buf, v_buf))
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    logits = lm_logits(x, params, spec)
    return logits, k_new, v_new
