"""Functional JAX transformer (Llama/Qwen2 family) with a paged KV cache.

Pure-functional, scan-over-layers (O(1) compile time in depth), bfloat16 on
the MXU with fp32 softmax/norm accumulations. Parameters and the KV cache are
sharded over a ("dp", "tp") mesh with XLA inserting the collectives
(all-reduce after attention-out and MLP-down projections) — the tpu-idiomatic
replacement for the reference engines' NCCL tensor parallelism (SURVEY.md
§2.7). RoPE uses HF's rotate-half convention so HF safetensors load directly.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Parameter init + sharding specs
# ---------------------------------------------------------------------------

def param_shapes(spec: ModelSpec) -> dict:
    h, d = spec.hidden_size, spec.head_dim
    nh, nkv, L = spec.num_heads, spec.num_kv_heads, spec.num_layers
    i = spec.intermediate_size
    shapes = {
        "embed": (spec.vocab_size, h),
        "final_norm": (h,),
        "layers": {
            "input_norm": (L, h),
            "post_attn_norm": (L, h),
            "wq": (L, h, nh * d),
            "wk": (L, h, nkv * d),
            "wv": (L, h, nkv * d),
            "wo": (L, nh * d, h),
            "w_gate": (L, h, i),
            "w_up": (L, h, i),
            "w_down": (L, i, h),
        },
    }
    if spec.qkv_bias:
        shapes["layers"]["bq"] = (L, nh * d)
        shapes["layers"]["bk"] = (L, nkv * d)
        shapes["layers"]["bv"] = (L, nkv * d)
    if not spec.tie_word_embeddings:
        shapes["lm_head"] = (h, spec.vocab_size)
    return shapes


def param_specs(spec: ModelSpec) -> dict:
    """PartitionSpecs: column-parallel qkv/gate/up, row-parallel o/down
    (Megatron layout — XLA adds the psum at row-parallel outputs)."""
    specs = {
        "embed": P(None, "tp"),
        "final_norm": P(None),
        "layers": {
            "input_norm": P(None, None),
            "post_attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
    }
    if spec.qkv_bias:
        specs["layers"]["bq"] = P(None, "tp")
        specs["layers"]["bk"] = P(None, "tp")
        specs["layers"]["bv"] = P(None, "tp")
    if not spec.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def init_params(spec: ModelSpec, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random init (bench/smoke). Real weights come from the safetensors
    loader (dynamo_tpu.engine.weights)."""
    shapes = param_shapes(spec)
    leaves, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(key, len(leaves))

    def init_one(shape, k):
        if len(shape) == 1 or shape[-1] == 1:
            return jnp.ones(shape, dtype)  # norm scales
        fan_in = shape[-2] if len(shape) > 1 else shape[-1]
        return (jax.random.normal(k, shape, dtype)
                * (1.0 / jnp.sqrt(fan_in)).astype(dtype))

    inited = [init_one(s, k) for s, k in zip(leaves, keys)]
    params = jax.tree.unflatten(treedef, inited)
    # Norm scales must be ones.
    params["final_norm"] = jnp.ones(shapes["final_norm"], dtype)
    params["layers"]["input_norm"] = jnp.ones(
        shapes["layers"]["input_norm"], dtype)
    params["layers"]["post_attn_norm"] = jnp.ones(
        shapes["layers"]["post_attn_norm"], dtype)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope_tables(positions: jax.Array, head_dim: int, theta: float
                ) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for HF rotate-half RoPE; positions [...]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    return cos, sin


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [..., heads, head_dim]; cos/sin [..., half] (broadcast over heads)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos - xf2 * sin
    out2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def dense_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           q_positions: jax.Array, kv_len_mask: jax.Array,
                           q_per_kv: int) -> jax.Array:
    """Prefill attention over freshly-computed K/V.

    q [B,S,Nh,D], k/v [B,S,Nkv,D], q_positions [B,S] (absolute), kv_len_mask
    [B,S] bool (valid kv slots). Causal by position. fp32 accumulation.
    GQA handled by grouping q heads (no materialized repeat).
    """
    b, s, nh, d = q.shape
    nkv = k.shape[2]
    qg = q.reshape(b, s, nkv, q_per_kv, d)
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    causal = (q_positions[:, None, None, :, None]
              >= q_positions[:, None, None, None, :])
    valid = kv_len_mask[:, None, None, None, :]
    scores = jnp.where(causal & valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, v)
    return out.reshape(b, s, nh, d)


def paged_decode_attention_xla(q: jax.Array, k_pages: jax.Array,
                               v_pages: jax.Array, page_table: jax.Array,
                               seq_lens: jax.Array, q_per_kv: int
                               ) -> jax.Array:
    """Reference/fallback decode attention (gather-based; CPU tests + any
    platform). q [B,Nh,D]; k_pages/v_pages [Nkv,P,page,D]; page_table
    [B,maxP]; seq_lens [B]. The Pallas kernel (attention.py) replaces this on
    TPU — it reads only live pages from HBM instead of gathering max_len."""
    b, nh, d = q.shape
    nkv, _, page, _ = k_pages.shape
    maxp = page_table.shape[1]
    k_all = k_pages[:, page_table]  # [Nkv,B,maxP,page,D]
    v_all = v_pages[:, page_table]
    k_all = k_all.reshape(nkv, b, maxp * page, d)
    v_all = v_all.reshape(nkv, b, maxp * page, d)
    qg = q.reshape(b, nkv, q_per_kv, d)
    scores = jnp.einsum("bngd,nbld->bngl", qg, k_all,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    positions = jnp.arange(maxp * page)[None, :]
    mask = (positions < seq_lens[:, None])[:, None, None, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngl,nbld->bngd", probs, v_all)
    return out.reshape(b, nh, d)


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------

def _split_heads(x, n, d):
    return x.reshape(*x.shape[:-1], n, d)


def prefill_forward(params: Params, spec: ModelSpec,
                    k_cache: jax.Array, v_cache: jax.Array,
                    tokens: jax.Array, positions: jax.Array,
                    page_table: jax.Array, seq_lens: jax.Array,
                    ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Process prompt chunks and write K/V into pages.

    tokens/positions [B,S] (S = bucket, multiple of page_size), page_table
    [B, S//page_size] (pages covering THIS chunk), seq_lens [B] (valid token
    counts). Returns (last_token_logits [B,V], k_cache, v_cache).
    """
    b, s = tokens.shape
    d = spec.head_dim
    page = k_cache.shape[3]
    x = params["embed"][tokens].astype(jnp.bfloat16)  # [B,S,H]
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    valid = jnp.arange(s)[None, :] < seq_lens[:, None]

    def layer_fn(x, scan_in):
        lp, k_pages_l, v_pages_l = scan_in
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = jnp.einsum("bsh,hd->bsd", h, lp["wq"],
                       preferred_element_type=jnp.bfloat16)
        k = jnp.einsum("bsh,hd->bsd", h, lp["wk"],
                       preferred_element_type=jnp.bfloat16)
        v = jnp.einsum("bsh,hd->bsd", h, lp["wv"],
                       preferred_element_type=jnp.bfloat16)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, spec.num_kv_heads, d)
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # Write K/V into this chunk's pages: cache is [Nkv, P, page, D].
        k_blocks = (k.reshape(b * (s // page), page, spec.num_kv_heads, d)
                    .transpose(2, 0, 1, 3))
        v_blocks = (v.reshape(b * (s // page), page, spec.num_kv_heads, d)
                    .transpose(2, 0, 1, 3))
        flat_pages = page_table.reshape(-1)
        k_pages_l = k_pages_l.at[:, flat_pages].set(k_blocks)
        v_pages_l = v_pages_l.at[:, flat_pages].set(v_blocks)
        attn = dense_causal_attention(q, k, v, positions, valid, spec.q_per_kv)
        attn = attn.reshape(b, s, -1)
        x = x + jnp.einsum("bsd,dh->bsh", attn, lp["wo"],
                           preferred_element_type=jnp.bfloat16)
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        gate = jnp.einsum("bsh,hi->bsi", h2, lp["w_gate"],
                          preferred_element_type=jnp.bfloat16)
        up = jnp.einsum("bsh,hi->bsi", h2, lp["w_up"],
                        preferred_element_type=jnp.bfloat16)
        ff = jax.nn.silu(gate.astype(jnp.float32)).astype(jnp.bfloat16) * up
        x = x + jnp.einsum("bsi,ih->bsh", ff, lp["w_down"],
                           preferred_element_type=jnp.bfloat16)
        return x, (k_pages_l, v_pages_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer_fn, x, (params["layers"], k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    # Last valid token per sequence.
    last_idx = jnp.maximum(seq_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    head = (params["embed"].T if spec.tie_word_embeddings
            else params["lm_head"])
    logits = jnp.einsum("bh,hv->bv", x_last, head,
                        preferred_element_type=jnp.float32)
    return logits, k_cache, v_cache


def decode_forward(params: Params, spec: ModelSpec,
                   k_cache: jax.Array, v_cache: jax.Array,
                   tokens: jax.Array, positions: jax.Array,
                   page_table: jax.Array, seq_lens: jax.Array,
                   attention_impl=None, write_mask: jax.Array | None = None,
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for the whole slot batch.

    tokens [B], positions [B] (absolute position of the new token), page_table
    [B, maxP], seq_lens [B] (lengths INCLUDING the new token). write_mask [B]
    bool (optional): rows with False scatter their K/V to the reserved
    scratch page 0 instead of their own pages (used by the window loop to
    freeze slots that hit page capacity mid-window). Returns
    (logits [B,V], k_cache, v_cache).
    """
    b = tokens.shape[0]
    d = spec.head_dim
    page = k_cache.shape[3]
    x = params["embed"][tokens].astype(jnp.bfloat16)  # [B,H]
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    # Target page slot for the new token.
    page_idx = positions // page
    page_off = positions % page
    dest_page = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]
    if write_mask is not None:
        dest_page = jnp.where(write_mask, dest_page, 0)
        page_off = jnp.where(write_mask, page_off, 0)
    attn_fn = attention_impl or paged_decode_attention_xla

    def layer_fn(x, scan_in):
        lp, k_pages_l, v_pages_l = scan_in
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)       # [B,Nh,D]
        k = _split_heads(k, spec.num_kv_heads, d)    # [B,Nkv,D]
        v = _split_heads(v, spec.num_kv_heads, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # Scatter the new K/V token into its page (cache [Nkv,P,page,D]).
        k_pages_l = k_pages_l.at[:, dest_page, page_off].set(k.transpose(1, 0, 2))
        v_pages_l = v_pages_l.at[:, dest_page, page_off].set(v.transpose(1, 0, 2))
        attn = attn_fn(q, k_pages_l, v_pages_l, page_table, seq_lens,
                       spec.q_per_kv)  # [B,Nh,D]
        attn = attn.reshape(b, -1)
        x = x + attn @ lp["wo"]
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        ff = (jax.nn.silu((h2 @ lp["w_gate"]).astype(jnp.float32))
              .astype(jnp.bfloat16) * (h2 @ lp["w_up"]))
        x = x + ff @ lp["w_down"]
        return x, (k_pages_l, v_pages_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer_fn, x, (params["layers"], k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    head = (params["embed"].T if spec.tie_word_embeddings
            else params["lm_head"])
    logits = jnp.einsum("bh,hv->bv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, k_cache, v_cache
