"""Model source resolution: preset | local directory | HF hub id.

Capability parity with reference lib/llm/src/hub.rs:311 and
local_model.rs:429: a model argument resolves, in order, to a built-in
preset, a local checkpoint directory, or a Hugging Face hub id — hub ids
are served from the local HF cache when present and downloaded via
``huggingface_hub.snapshot_download`` when the environment has network
access (air-gapped TPU pods get a clear error naming the cache path to
pre-populate instead of a hang).
"""

from __future__ import annotations

import os

from dynamo_tpu.engine.config import PRESETS, ModelSpec
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("hub")

_CHECKPOINT_FILES = ("config.json",)


def looks_like_checkpoint_dir(path: str) -> bool:
    return os.path.isdir(path) and all(
        os.path.exists(os.path.join(path, f)) for f in _CHECKPOINT_FILES)


def resolve_model(model: str, revision: str | None = None,
                  allow_download: bool = True) -> tuple[ModelSpec, str | None]:
    """Resolve ``model`` to (spec, checkpoint_dir). checkpoint_dir is None
    for presets (random-weight serving)."""
    if model in PRESETS:
        return PRESETS[model], None
    if looks_like_checkpoint_dir(model):
        return ModelSpec.from_hf_config(model), model
    if os.path.sep in model and not model.count("/") == 1:
        raise FileNotFoundError(
            f"{model!r} is not a preset ({sorted(PRESETS)}), not a local "
            f"checkpoint directory, and not a hub id")
    # Treat as a hub id: local cache first, then (optionally) download.
    from huggingface_hub import snapshot_download
    from huggingface_hub.errors import (HFValidationError,
                                        LocalEntryNotFoundError)
    try:
        path = snapshot_download(model, revision=revision,
                                 local_files_only=True,
                                 allow_patterns=["*.json", "*.safetensors",
                                                 "tokenizer*"])
        log.info("resolved %s from local HF cache: %s", model, path)
        return ModelSpec.from_hf_config(path), path
    except HFValidationError as exc:
        raise FileNotFoundError(
            f"{model!r} is not a preset ({sorted(PRESETS)}), not a local "
            f"checkpoint directory, and not a valid hub id ({exc})") from exc
    except LocalEntryNotFoundError:
        pass
    if not allow_download:
        raise FileNotFoundError(
            f"{model!r} is not in the local HF cache and downloads are "
            f"disabled; pre-populate the cache (HF_HOME="
            f"{os.environ.get('HF_HOME', '~/.cache/huggingface')})")
    try:
        path = snapshot_download(model, revision=revision,
                                 allow_patterns=["*.json", "*.safetensors",
                                                 "tokenizer*"])
    except Exception as exc:  # noqa: BLE001 — no-egress pods land here
        raise FileNotFoundError(
            f"could not download {model!r} ({type(exc).__name__}: {exc}); "
            f"on air-gapped pods pre-populate the HF cache or pass a local "
            f"checkpoint directory") from exc
    log.info("downloaded %s -> %s", model, path)
    return ModelSpec.from_hf_config(path), path
