"""Multi-host single engine: SPMD leader/follower runner drive.

Capability parity with the reference's multi-node single engine
(``lib/llm/src/engines.rs:31-44`` ``MultiNodeConfig{num_nodes, node_rank,
leader_addr}`` + the etcd leader/worker barrier,
``lib/runtime/src/utils/leader_worker_barrier.rs:137,230``), designed
TPU-first: one JAX computation spans every host's chips via a global
``Mesh`` (multi-controller SPMD), so tensor/pipeline shardings ride
ICI/DCN through XLA collectives — there is no NCCL/MPI layer to port.

How it works:

- Every host calls :func:`initialize` (``jax.distributed.initialize``),
  making ``jax.devices()`` the global device list. The ``ModelRunner``
  builds its mesh over those devices unchanged.
- JAX multi-controller semantics require every process to issue the SAME
  jit calls in the SAME order. Only the leader runs the serving engine
  (scheduler, HTTP, KV ledger); its runner is wrapped in
  :class:`LeaderRunner`, which publishes each device call's control
  payload (numpy arrays, a few KB) on the coordinator pub/sub before
  executing it.
- Followers run :func:`run_follower`: a replay loop that applies the same
  calls to their own ``ModelRunner`` replica. Control payloads are
  identical, the rng is threaded through the jit state, so every process
  dispatches an identical program and XLA's collectives line up.
- Bring-up is coordinated by the existing leader/worker barrier: the
  leader blocks until every follower has built its runner and subscribed,
  so no dispatch can be published before a follower is listening.

Scope: the serving hot path (``prefill_batch``, ``decode_window``,
``prefill``, ``embed``) AND the KV parcel plane (``extract_pages``,
``insert_pages``): extracts compile with a replicated output in
multi-controller mode (XLA all-gathers the pages over ICI/DCN, so the
leader's host fetch is local), inserts replay with the parcel bytes in
the dispatch payload — disaggregation and host/disk tiering therefore
compose with multi-host engines (the north-star configuration:
BASELINE.md, 70B disaggregated across hosts).
"""

from __future__ import annotations

import asyncio
import os
import queue
import threading
from typing import Any

import numpy as np

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("multihost")

DISPATCH_SUBJECT = "mh.{group}.dispatch"
BARRIER_ID = "mh/{group}/bringup"


def initialize(coordinator_address: str, num_processes: int,
               process_id: int) -> None:
    """``jax.distributed.initialize`` with CPU-backend collectives enabled
    (tests run N processes on one machine with gloo; on TPU pods the
    backend does this natively over ICI/DCN)."""
    import jax

    # Decide from the environment, NOT jax.default_backend(): that call
    # would initialise the XLA backend, which must not happen before
    # distributed.initialize.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("multihost initialized: process %d/%d, %d global devices",
             process_id, num_processes, jax.device_count())


# -- wire helpers -------------------------------------------------------------

def _pack_array(a) -> dict | None:
    if a is None:
        return None
    a = np.ascontiguousarray(a)
    return {"b": a.tobytes(), "dtype": str(a.dtype), "shape": list(a.shape)}


def _unpack_array(d: dict | None):
    if d is None:
        return None
    if d["dtype"] == "bfloat16":  # KV parcels; not a numpy-native name
        import ml_dtypes

        dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        dtype = np.dtype(d["dtype"])
    return np.frombuffer(d["b"], dtype=dtype).reshape(d["shape"])


def _pack_seq(s) -> dict:
    return {"tokens": _pack_array(s.tokens), "start_pos": int(s.start_pos),
            "chunk_pages": _pack_array(s.chunk_pages),
            "hist_pages": _pack_array(s.hist_pages),
            "sampling": [float(s.sampling[0]), int(s.sampling[1]),
                         float(s.sampling[2])],
            "logprobs": bool(s.logprobs),
            "penalties": [float(s.penalties[0]), float(s.penalties[1])],
            "seed": None if s.seed is None else int(s.seed),
            "embeds": _pack_array(s.embeds),
            "embeds_mask": _pack_array(s.embeds_mask)}


def _unpack_seq(d: dict):
    from dynamo_tpu.engine.runner import PrefillSeq
    t, k, p = d["sampling"]
    fp, pp = d.get("penalties", (0.0, 0.0))
    return PrefillSeq(tokens=_unpack_array(d["tokens"]),
                      start_pos=d["start_pos"],
                      chunk_pages=_unpack_array(d["chunk_pages"]),
                      hist_pages=_unpack_array(d["hist_pages"]),
                      sampling=(float(t), int(k), float(p)),
                      logprobs=d["logprobs"],
                      penalties=(float(fp), float(pp)),
                      seed=d.get("seed"),
                      embeds=_unpack_array(d.get("embeds")),
                      embeds_mask=_unpack_array(d.get("embeds_mask")))


class LeaderRunner:
    """Wraps the leader's ModelRunner: every device call is published to
    the follower replay stream (in submission order — one event loop, one
    coordinator connection) and then executed locally. Engine code treats
    it exactly like a ModelRunner."""

    def __init__(self, inner, client, loop: asyncio.AbstractEventLoop,
                 group: str):
        self._inner = inner
        self._client = client
        self._loop = loop
        self._subject = DISPATCH_SUBJECT.format(group=group)
        self._seq = 0
        self._prev_fut = None

    def __getattr__(self, name: str) -> Any:
        # Non-dispatching surface (mesh, num_pages, bucket_pages_for, ...)
        # passes straight through.
        return getattr(self._inner, name)

    def _publish(self, msg: dict) -> None:
        self._seq += 1
        msg["n"] = self._seq
        fut = asyncio.run_coroutine_threadsafe(
            self._client.publish(self._subject, msg), self._loop)
        # Surface transport failures instead of silently diverging (a
        # dropped dispatch would desynchronize every follower) — but
        # pipelined by one: await the PREVIOUS dispatch's ack, not this
        # one's, so the engine thread doesn't pay a coordinator RTT
        # inline per window. Ordering is already fixed by the single
        # event loop + connection; fail-fast just lands one window late.
        prev, self._prev_fut = self._prev_fut, fut
        if prev is not None:
            prev.result(timeout=30.0)

    def pending_ack(self):
        """The newest dispatch's unacknowledged publish future (or None).
        The stop path awaits it before declaring shutdown complete — a
        transport failure on the LAST dispatch before idle/stop would
        otherwise never surface, leaving followers silently one window
        behind. (Async-safe: callers on the event loop wrap it with
        asyncio.wrap_future instead of blocking on .result().)"""
        fut, self._prev_fut = self._prev_fut, None
        return fut

    def prefill_batch(self, seqs, slots=None, count_rows=None, fetch=True):
        self._publish({"m": "prefill_batch",
                       "seqs": [_pack_seq(s) for s in seqs],
                       "slots": None if slots is None
                       else [int(x) for x in slots],
                       "count_rows": _pack_array(count_rows)})
        return self._inner.prefill_batch(seqs, slots, count_rows,
                                         fetch=fetch)

    def prefill_chunk_async(self, seq):
        """Stall-free chunked prefill: followers replay the chunk
        dispatch for its collectives; nobody fetches (the sampled token
        is discarded on every process)."""
        self._publish({"m": "prefill_chunk", "seq": _pack_seq(seq)})
        return self._inner.prefill_chunk_async(seq)

    def set_count_rows(self, slots, rows):
        self._publish({"m": "set_count_rows",
                       "slots": [int(x) for x in slots],
                       "rows": _pack_array(rows)})
        return self._inner.set_count_rows(slots, rows)

    def prefill(self, tokens, start_pos, chunk_pages, hist_pages, sampling,
                penalties=(0.0, 0.0), count_row=None, seed=None,
                embeds=None, embeds_mask=None):
        from dynamo_tpu.engine.runner import PrefillSeq
        self._publish({"m": "prefill", "seq": _pack_seq(PrefillSeq(
            tokens=np.asarray(tokens, np.int32), start_pos=start_pos,
            chunk_pages=np.asarray(chunk_pages, np.int32),
            hist_pages=hist_pages, sampling=sampling,
            penalties=penalties, seed=seed,
            embeds=embeds, embeds_mask=embeds_mask)),
            "count_row": _pack_array(count_row)})
        return self._inner.prefill(tokens, start_pos, chunk_pages,
                                   hist_pages, sampling, penalties,
                                   count_row, seed, embeds, embeds_mask)

    def decode_window(self, packed: np.ndarray, window: int):
        self._publish({"m": "decode_window", "packed": _pack_array(packed),
                       "window": int(window)})
        return self._inner.decode_window(packed, window)

    def decode_spec_window(self, packed: np.ndarray, m_outer: int, k: int):
        self._publish({"m": "decode_spec_window",
                       "packed": _pack_array(packed),
                       "m_outer": int(m_outer), "k": int(k)})
        return self._inner.decode_spec_window(packed, m_outer, k)

    def seed_history(self, entries):
        self._publish({"m": "seed_history", "entries": [
            [int(slot), _pack_array(np.asarray(toks, np.int32)),
             int(start), bool(final),
             (None if ftok is None else int(ftok))]
            for slot, toks, start, final, ftok in entries]})
        return self._inner.seed_history(entries)

    def embed(self, token_lists, pooling: str = "last"):
        self._publish({"m": "embed",
                       "token_lists": [[int(t) for t in row]
                                       for row in token_lists],
                       "pooling": pooling})
        return self._inner.embed(token_lists, pooling)

    # KV parcel extract/insert (disaggregation + tiering): the extract
    # gather runs on EVERY process with a replicated output (the runner
    # compiles it with out_shardings=P() in multi-controller mode, so XLA
    # all-gathers the pages over ICI/DCN) — the leader's host fetch is
    # then local. Inserts replay with the parcel bytes in the dispatch
    # payload (identical on every host, like any other control array).
    def extract_pages_async(self, pages):
        self._publish({"m": "extract_pages",
                       "pages": [int(p) for p in pages]})
        return self._inner.extract_pages_async(pages)

    def extract_pages(self, pages):
        return self._inner.finalize_extract(self.extract_pages_async(pages))

    def insert_pages(self, kv, pages):
        self._publish({"m": "insert_pages", "kv": _pack_array(kv),
                       "pages": [int(p) for p in pages]})
        return self._inner.insert_pages(kv, pages)


async def leader_barrier(client, group: str, num_followers: int,
                         shape: dict, timeout: float = 300.0) -> None:
    """Block until every follower has its runner built and subscription
    live. ``shape`` (model/mesh facts) is cross-checked by followers."""
    from dynamo_tpu.runtime.barrier import LeaderBarrier
    await LeaderBarrier(client, BARRIER_ID.format(group=group),
                        num_followers).sync(shape, timeout=timeout)


async def run_follower(config, client, group: str, node_rank: int,
                       params=None, seed: int = 0) -> None:
    """Build the runner replica, join the bring-up barrier, then replay
    leader dispatches until a stop message (or cancellation).

    Runner calls execute on a dedicated thread (device work can block for
    seconds during compilation; the event loop must keep servicing the
    coordinator connection's keepalives)."""
    import dataclasses

    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.runtime.barrier import WorkerBarrier

    # Order matters: subscribe FIRST (dispatches published after the
    # barrier buffer in the subscription queue), then cross-check the
    # leader's shape, then build. The leader's barrier payload carries its
    # ACTUAL num_pages so auto-sizing can never diverge across hosts —
    # a one-page difference would change the jitted program and corrupt
    # every cross-host collective.
    sub = await client.subscribe(DISPATCH_SUBJECT.format(group=group))
    shape = await WorkerBarrier(
        client, BARRIER_ID.format(group=group), str(node_rank)).sync(
            {"rank": node_rank})
    expect = {"model": config.model.name,
              "mesh": [config.dp, config.pp, config.sp, config.tp]}
    got = {k: shape.get(k) for k in expect}
    if got != expect:
        raise RuntimeError(f"follower/leader config mismatch: leader "
                           f"published {got}, follower built {expect}")
    if shape.get("num_pages"):
        config = dataclasses.replace(config, num_pages=shape["num_pages"])
    # Build off the event loop: weight load + sharded upload blocks for
    # seconds and the coordinator keepalives must keep flowing.
    runner = await asyncio.get_running_loop().run_in_executor(
        None, lambda: ModelRunner(config, params=params, seed=seed))
    log.info("follower %d: runner built (%d pages), replaying dispatches",
             node_rank, runner.num_pages)

    loop = asyncio.get_running_loop()
    work: queue.Queue = queue.Queue()
    done = asyncio.Event()  # set (thread-safely) when the replay thread exits
    errors: list[BaseException] = []

    def replay_loop() -> None:
        n_seen = 0
        while True:
            msg = work.get()
            if msg is None or msg.get("m") == "stop":
                break
            try:
                n = msg.get("n", 0)
                if n_seen and n != n_seen + 1:
                    raise RuntimeError(
                        f"dispatch stream gap: saw {n} after {n_seen}")
                n_seen = n
                m = msg["m"]
                if m == "prefill_batch":
                    runner.prefill_batch(
                        [_unpack_seq(s) for s in msg["seqs"]], msg["slots"],
                        _unpack_array(msg.get("count_rows")))
                elif m == "set_count_rows":
                    runner.set_count_rows(msg["slots"],
                                          _unpack_array(msg["rows"]))
                elif m == "prefill":
                    s = _unpack_seq(msg["seq"])
                    runner.prefill(s.tokens, s.start_pos, s.chunk_pages,
                                   s.hist_pages, s.sampling, s.penalties,
                                   _unpack_array(msg.get("count_row")),
                                   s.seed, s.embeds, s.embeds_mask)
                elif m == "prefill_chunk":
                    # Intermediate prefill chunk: dispatch-only on every
                    # process (no fetch anywhere — the sampled token is
                    # discarded; KV chains on device).
                    runner.prefill_chunk_async(_unpack_seq(msg["seq"]))
                elif m == "decode_window":
                    runner.decode_window(_unpack_array(msg["packed"]),
                                         msg["window"])
                elif m == "decode_spec_window":
                    runner.decode_spec_window(_unpack_array(msg["packed"]),
                                              msg["m_outer"], msg["k"])
                elif m == "seed_history":
                    runner.seed_history([
                        (slot, _unpack_array(toks), start, final, ftok)
                        for slot, toks, start, final, ftok
                        in msg["entries"]])
                elif m == "embed":
                    runner.embed(msg["token_lists"], msg["pooling"])
                elif m == "extract_pages":
                    # Dispatch the (replicated-output) gather so the
                    # leader's all-gather has peers; the result itself is
                    # only fetched leader-side.
                    runner.extract_pages_async(msg["pages"])
                elif m == "insert_pages":
                    runner.insert_pages(_unpack_array(msg["kv"]),
                                        msg["pages"])
                else:
                    raise RuntimeError(f"unknown dispatch {m!r}")
            except BaseException as exc:  # noqa: BLE001 — report and die
                errors.append(exc)
                break
        loop.call_soon_threadsafe(done.set)

    thread = threading.Thread(target=replay_loop, name="mh-replay",
                              daemon=True)
    thread.start()
    sub_iter = sub.__aiter__()
    died = asyncio.ensure_future(done.wait())  # completes at most once
    try:
        # Race each subscription read against replay-thread death: a
        # replay error during an idle stretch must surface immediately,
        # not after the next dispatch happens to arrive.
        while not done.is_set():
            get_next = asyncio.ensure_future(sub_iter.__anext__())
            finished, _ = await asyncio.wait(
                {get_next, died}, return_when=asyncio.FIRST_COMPLETED)
            if get_next not in finished:
                get_next.cancel()
                break
            event = get_next.result()
            work.put(event["payload"])
            if event["payload"].get("m") == "stop":
                break
    finally:
        died.cancel()
        work.put(None)
        await sub.cancel()
    try:
        # Bounded: the replay thread can be wedged inside a cross-host
        # collective whose peers died (leader crash mid-window). It is a
        # daemon thread — after the grace period let process teardown
        # reap it rather than hanging shutdown forever.
        await asyncio.wait_for(done.wait(), timeout=60.0)
    except asyncio.TimeoutError:
        log.warning("follower %d: replay thread did not drain in 60s "
                    "(peer death mid-collective?); abandoning it",
                    node_rank)
    if errors:
        raise errors[0]
    log.info("follower %d: stopped", node_rank)
