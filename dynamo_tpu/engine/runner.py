"""ModelRunner: compiled, sharded prefill/decode steps over a device mesh.

Owns the mesh (("dp","tp"), reference §2.7 TP delegated-to-engine -> here
native via jax.sharding), the sharded parameters, the paged KV device arrays,
and the jit-compiled step functions:

- ``prefill(chunk)``: length-bucketed (one compiled program per bucket);
  supports history pages so long prompts prefill in chunks (chunked prefill,
  SURVEY.md §5.7 parity) and cached prefixes are skipped, attending to prior
  pages via the same paged read path as decode;
- ``decode_step``: one token for the whole slot batch + batched sampling.

KV arrays are donated through every call so XLA updates them in place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.model import (
    dense_causal_attention,
    init_params,
    paged_decode_attention_xla,
    param_specs,
    prefill_forward,
    decode_forward,
)
from dynamo_tpu.engine.sampler import sample_tokens
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("runner")


class ModelRunner:
    def __init__(self, config: EngineConfig, params=None,
                 devices: list | None = None, seed: int = 0):
        self.config = config
        spec = config.model
        self.spec = spec
        devices = devices if devices is not None else jax.devices()
        total = config.dp * config.tp
        if len(devices) < total:
            raise ValueError(f"need {total} devices, have {len(devices)}")
        dev_array = np.array(devices[:total]).reshape(config.dp, config.tp)
        self.mesh = Mesh(dev_array, ("dp", "tp"))
        self._sized_pages(devices[0])

        # Shard or init parameters.
        pspecs = param_specs(spec)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P))
        if params is None:
            key = jax.random.key(seed)
            with jax.default_device(jax.devices("cpu")[0]):
                params = init_params(spec, key)
        self.params = jax.device_put(params, shardings)

        # KV cache arrays [L, Nkv, P, page, D]: kv heads sharded over tp, and
        # [page, D] contiguous per (head, page) for clean Pallas DMAs.
        kv_spec = P(None, "tp", None, None, None)
        self.kv_sharding = NamedSharding(self.mesh, kv_spec)
        kv_shape = (spec.num_layers, spec.num_kv_heads, self.num_pages,
                    config.page_size, spec.head_dim)
        self.k_cache = jax.device_put(
            jnp.zeros(kv_shape, jnp.bfloat16), self.kv_sharding)
        self.v_cache = jax.device_put(
            jnp.zeros(kv_shape, jnp.bfloat16), self.kv_sharding)

        self._prefill_cache: dict = {}
        self._decode_fn = None
        self._rng = jax.random.key(seed + 1)
        self._attention_impl = self._pick_attention()

    # -- setup ---------------------------------------------------------------
    def _sized_pages(self, device) -> None:
        cfg = self.config
        if cfg.num_pages is not None:
            self.num_pages = cfg.num_pages
            return
        # Size the KV pool from free HBM after params (reference: engines'
        # gpu_memory_utilization; here hbm_kv_budget_frac).
        try:
            stats = device.memory_stats()
            free = stats["bytes_limit"] - stats["bytes_in_use"]
        except Exception:  # noqa: BLE001 — CPU tests have no memory_stats
            free = 2 << 30
        param_bytes = self.spec.num_params() * 2 // max(1, cfg.tp * cfg.dp)
        budget = max(64 << 20, int((free - param_bytes) * cfg.hbm_kv_budget_frac))
        page_bytes = (self.spec.kv_bytes_per_token() * cfg.page_size
                      // max(1, cfg.tp))
        self.num_pages = max(16, budget // max(1, page_bytes))
        log.info("KV pool: %d pages of %d tokens (%.1f GiB)", self.num_pages,
                 cfg.page_size, self.num_pages * page_bytes / (1 << 30))

    def _pick_attention(self):
        backend = self.config.attention_backend
        if backend == "auto":
            backend = ("pallas" if jax.devices()[0].platform == "tpu"
                       else "xla")
        if backend == "pallas":
            if self.spec.head_dim % 128 != 0:
                # Mosaic DMA slices need the trailing dim 128-aligned; D=64
                # models (qwen2.5-0.5b etc.) use the XLA path.
                log.info("head_dim %d not 128-aligned; pallas kernel disabled",
                         self.spec.head_dim)
                return paged_decode_attention_xla
            try:
                from dynamo_tpu.engine.attention import paged_decode_attention_pallas
                return paged_decode_attention_pallas
            except Exception:  # noqa: BLE001
                log.exception("pallas attention unavailable; using xla")
        return paged_decode_attention_xla

    # -- compiled steps -------------------------------------------------------
    def _get_prefill(self, bucket: int, with_history: bool):
        key = (bucket, with_history)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        cfg = self.config

        def step(params, k_cache, v_cache, tokens, positions, page_table,
                 seq_lens, hist_table, hist_lens):
            if with_history:
                logits, k_cache, v_cache = _prefill_with_history(
                    params, spec, k_cache, v_cache, tokens, positions,
                    page_table, seq_lens, hist_table, hist_lens,
                    self._attention_impl)
            else:
                logits, k_cache, v_cache = prefill_forward(
                    params, spec, k_cache, v_cache, tokens, positions,
                    page_table, seq_lens)
            return logits, k_cache, v_cache

        fn = jax.jit(step, donate_argnums=(1, 2))
        self._prefill_cache[key] = fn
        return fn

    def _get_decode(self):
        if self._decode_fn is not None:
            return self._decode_fn
        spec = self.spec

        def step(params, k_cache, v_cache, tokens, positions, page_table,
                 seq_lens, temperature, top_k, top_p, rng):
            logits, k_cache, v_cache = decode_forward(
                params, spec, k_cache, v_cache, tokens, positions,
                page_table, seq_lens, attention_impl=self._attention_impl)
            rng, sub = jax.random.split(rng)
            sampled = sample_tokens(logits, temperature, top_k, top_p, sub)
            return sampled, k_cache, v_cache, rng

        self._decode_fn = jax.jit(step, donate_argnums=(1, 2))
        return self._decode_fn

    # -- public API (blocking; called from the engine thread) -----------------
    def prefill(self, tokens: np.ndarray, start_pos: int,
                chunk_pages: np.ndarray, hist_pages: np.ndarray | None,
                sampling: tuple[float, int, float]) -> tuple[int, jax.Array]:
        """Prefill one chunk of one sequence; returns (sampled_token, logits).

        tokens: [n] the chunk's tokens; start_pos: absolute position of
        tokens[0]; chunk_pages: pages covering the chunk; hist_pages: pages of
        the context before the chunk (None = fresh prompt).
        """
        cfg = self.config
        n = len(tokens)
        bucket = cfg.bucket_for(n)
        page = cfg.page_size
        bucket_pages = bucket // page
        tok = np.zeros((1, bucket), np.int32)
        tok[0, :n] = tokens
        pos = np.zeros((1, bucket), np.int32)
        pos[0, :n] = np.arange(start_pos, start_pos + n)
        pos[0, n:] = start_pos + n - 1  # harmless pad positions
        # Pad rows stay 0 = the allocator's RESERVED scratch page, so padded
        # block scatters land there — padding with a live page would create
        # duplicate scatter indices whose XLA write order is unspecified.
        ptab = np.zeros((1, bucket_pages), np.int32)
        ptab[0, :len(chunk_pages)] = chunk_pages
        lens = np.array([n], np.int32)
        with_history = hist_pages is not None and len(hist_pages) > 0
        maxp = cfg.max_pages_per_seq
        htab = np.zeros((1, maxp), np.int32)
        hlens = np.zeros((1,), np.int32)
        if with_history:
            htab[0, :len(hist_pages)] = hist_pages
            hlens[0] = start_pos
        fn = self._get_prefill(bucket, with_history)
        with self.mesh:
            logits, self.k_cache, self.v_cache = fn(
                self.params, self.k_cache, self.v_cache, tok, pos, ptab,
                lens, htab, hlens)
            temp, tk, tp = sampling
            self._rng, sub = jax.random.split(self._rng)
            sampled = sample_tokens(
                logits, jnp.array([temp], jnp.float32),
                jnp.array([tk], jnp.int32), jnp.array([tp], jnp.float32), sub)
        return int(jax.device_get(sampled)[0]), logits

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               page_table: np.ndarray, seq_lens: np.ndarray,
               temperature: np.ndarray, top_k: np.ndarray,
               top_p: np.ndarray) -> np.ndarray:
        """One decode step over the slot batch; returns sampled tokens [B]."""
        fn = self._get_decode()
        with self.mesh:
            sampled, self.k_cache, self.v_cache, self._rng = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(page_table), jnp.asarray(seq_lens),
                jnp.asarray(temperature), jnp.asarray(top_k),
                jnp.asarray(top_p), self._rng)
        return np.asarray(jax.device_get(sampled))


def _prefill_with_history(params, spec, k_cache, v_cache, tokens, positions,
                          page_table, seq_lens, hist_table, hist_lens,
                          attention_impl):
    """Chunked prefill: like prefill_forward but queries also attend to the
    sequence's earlier pages (read via the paged path)."""
    import jax
    import jax.numpy as jnp
    from dynamo_tpu.engine.model import (
        _split_heads, apply_rope, rms_norm, rope_tables)

    b, s = tokens.shape
    d = spec.head_dim
    nkv = spec.num_kv_heads
    page = k_cache.shape[3]
    x = params["embed"][tokens].astype(jnp.bfloat16)
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    valid = jnp.arange(s)[None, :] < seq_lens[:, None]
    maxp = hist_table.shape[1]

    def layer_fn(x, scan_in):
        lp, k_pages_l, v_pages_l = scan_in
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = jnp.einsum("bsh,hd->bsd", h, lp["wq"],
                       preferred_element_type=jnp.bfloat16)
        k = jnp.einsum("bsh,hd->bsd", h, lp["wk"],
                       preferred_element_type=jnp.bfloat16)
        v = jnp.einsum("bsh,hd->bsd", h, lp["wv"],
                       preferred_element_type=jnp.bfloat16)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, nkv, d)
        v = _split_heads(v, nkv, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_blocks = (k.reshape(b * (s // page), page, nkv, d)
                    .transpose(2, 0, 1, 3))
        v_blocks = (v.reshape(b * (s // page), page, nkv, d)
                    .transpose(2, 0, 1, 3))
        flat = page_table.reshape(-1)
        k_pages_l = k_pages_l.at[:, flat].set(k_blocks)
        v_pages_l = v_pages_l.at[:, flat].set(v_blocks)
        # In-chunk causal scores (grouped GQA, no repeat).
        qg = q.reshape(b, s, nkv, spec.q_per_kv, d)
        chunk_scores = jnp.einsum("bqngd,bknd->bngqk", qg, k,
                                  preferred_element_type=jnp.float32)
        causal = (positions[:, None, None, :, None]
                  >= positions[:, None, None, None, :])
        chunk_scores = jnp.where(causal & valid[:, None, None, None, :],
                                 chunk_scores, -1e30)
        # History scores over prior pages ([Nkv,P,page,D] cache).
        k_hist = k_pages_l[:, hist_table].reshape(nkv, b, maxp * page, d)
        v_hist = v_pages_l[:, hist_table].reshape(nkv, b, maxp * page, d)
        hist_scores = jnp.einsum("bqngd,nbld->bngql", qg, k_hist,
                                 preferred_element_type=jnp.float32)
        hist_valid = (jnp.arange(maxp * page)[None, :]
                      < hist_lens[:, None])[:, None, None, None, :]
        hist_scores = jnp.where(hist_valid, hist_scores, -1e30)
        scores = jnp.concatenate([hist_scores, chunk_scores], axis=-1)
        scores = scores / jnp.sqrt(jnp.float32(d))
        probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
        p_hist, p_chunk = jnp.split(probs, [maxp * page], axis=-1)
        attn = (jnp.einsum("bngql,nbld->bqngd", p_hist, v_hist)
                + jnp.einsum("bngqk,bknd->bqngd", p_chunk, v))
        attn = attn.reshape(b, s, -1)
        x = x + jnp.einsum("bsd,dh->bsh", attn, lp["wo"],
                           preferred_element_type=jnp.bfloat16)
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        gate = jnp.einsum("bsh,hi->bsi", h2, lp["w_gate"],
                          preferred_element_type=jnp.bfloat16)
        up = jnp.einsum("bsh,hi->bsi", h2, lp["w_up"],
                        preferred_element_type=jnp.bfloat16)
        ff = jax.nn.silu(gate.astype(jnp.float32)).astype(jnp.bfloat16) * up
        x = x + jnp.einsum("bsi,ih->bsh", ff, lp["w_down"],
                           preferred_element_type=jnp.bfloat16)
        return x, (k_pages_l, v_pages_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer_fn, x, (params["layers"], k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    last_idx = jnp.maximum(seq_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    head = (params["embed"].T if spec.tie_word_embeddings
            else params["lm_head"])
    logits = jnp.einsum("bh,hv->bv", x_last, head,
                        preferred_element_type=jnp.float32)
    return logits, k_cache, v_cache
