"""ModelRunner: compiled, sharded prefill/decode steps over a device mesh.

Owns the mesh (("dp","tp"), reference §2.7 TP delegated-to-engine -> here
native via jax.sharding), the sharded parameters, the paged KV device arrays,
and the jit-compiled step functions:

- ``prefill_batch``: length-bucketed, batch-bucketed prefill of whole
  prompts (one compiled program per (bucket, batch, with_history)); supports
  history pages so long prompts prefill in chunks (chunked prefill, SURVEY.md
  §5.7 parity) and cached prefixes are skipped, attending to prior pages via
  the same paged read path as decode. First-token sampling is fused into the
  program (no separate sampler dispatch).
- ``decode_window``: M decode steps for the whole slot batch in ONE device
  program (lax.scan over steps): tokens chain on-device, positions/lengths
  advance in-graph, sampling per step. The host uploads a single packed
  int32 control array per window and reads back the [M,B] sampled tokens
  asynchronously — the design keeps host<->device round-trips OFF the
  per-token path (the reference's GPU engines rely on CUDA-graph replay for
  the same reason; XLA's equivalent is one big compiled window).
- page-table width bucketing: the decode window is compiled per power-of-2
  page-table width, so the XLA gather attention reads ~live pages instead of
  max_pages_per_seq for every sequence.

KV arrays are donated through every call so XLA updates them in place.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine import perf
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.kv_quant import (KV_SCALE_BYTES, QuantKV, pack_parcel,
                                        parcel_to_bf16, quantize_np,
                                        scatter_tokens, unpack_parcel)
from dynamo_tpu.engine.model import (
    dense_causal_attention,
    init_params,
    paged_decode_attention_xla,
    param_specs,
    prefill_forward,
    decode_forward,
    decode_window_step,
)
from dynamo_tpu.engine.sampler import sample_tokens, sample_tokens_per_row
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("runner")

# Packed per-window control array columns (int32; floats bitcast).
PK_OVERRIDE = 0   # 1 -> take PK_TOKEN instead of the chained device token
PK_TOKEN = 1
PK_POS = 2        # absolute position of the token to be written this window
PK_SEQLEN = 3     # length INCLUDING that token; 0 -> slot inactive
PK_TOPK = 4
PK_TEMP = 5       # float32 bits
PK_TOPP = 6       # float32 bits
PK_CAP = 7        # position capacity = allocated pages * page_size; a slot
                  # freezes in-graph when its position reaches this
PK_LOGPROB = 8    # 1 -> this slot wants logprobs (window computes them
                  # when ANY slot asks; per-slot filtering is host-side)
PK_FREQPEN = 9    # float32 bits: OpenAI frequency_penalty (0 = off)
PK_PRESPEN = 10   # float32 bits: OpenAI presence_penalty (0 = off)
PK_SEED = 11      # int32 sampling seed (meaningful when PK_SEEDED)
PK_SEEDED = 12    # 1 -> slot uses a per-request seeded rng stream
PK_ADAPTER = 13   # resident LoRA adapter slot id (0 = base model; the
                  # gathered A/B correction reads this row's stacks —
                  # engine/lora.py)
PK_PREFIX = 14    # page table starts here

TOP_LOGPROBS = 8  # alternatives returned when logprobs are requested

SEED_MASK = 0x7FFFFFFF  # seeds ride int32 control columns: 31 usable bits


def mask_seed(seed: int) -> int:
    """The ONE place a request seed maps to its on-device value — the
    prefill and window paths must fold the identical base key or
    preemption-recompute would diverge from the original draws."""
    return int(seed) & SEED_MASK

_PF_HDR = 12      # prefill packed-array header columns (7 freq-penalty
                  # bits, 8 pres-penalty bits, 9 seed, 10 seeded flag,
                  # 11 adapter slot id)


def _logprobs_of(logits: jax.Array, sampled: jax.Array):
    """(chosen logprob [B], top values [B,K], top ids [B,K]) from raw
    logits — log-softmax via one logsumexp, no full-vocab sort."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    chosen = jnp.take_along_axis(logits, sampled[:, None], axis=1)[:, 0]
    top_v, top_i = jax.lax.top_k(logits, TOP_LOGPROBS)
    return chosen - lse, top_v - lse[:, None], top_i


@dataclasses.dataclass
class PrefillSeq:
    """One whole-prompt (or final-chunk) prefill row."""
    tokens: np.ndarray          # [n] chunk tokens
    start_pos: int              # absolute position of tokens[0]
    chunk_pages: np.ndarray     # pages covering the chunk
    hist_pages: np.ndarray | None  # pages before the chunk (None = fresh)
    sampling: tuple[float, int, float]  # (temperature, top_k, top_p)
    logprobs: bool = False      # row wants first-token logprobs
    penalties: tuple[float, float] = (0.0, 0.0)  # (frequency, presence)
    seed: int | None = None     # per-request sampling seed
    # Multimodal: encoder embeddings [n, H] + bool mask [n] (n =
    # len(tokens)): where the mask is set, the embedding row replaces the
    # token table's row (the token id there is a placeholder).
    embeds: np.ndarray | None = None
    embeds_mask: np.ndarray | None = None
    # Resident LoRA adapter slot (0 = base model; engine/lora.py).
    adapter_id: int = 0


def _mh_put(value, sharding):
    """Place a host-resident full array onto the mesh. In multi-controller
    mode (jax.process_count() > 1, multi-host serving) a plain device_put
    of host data onto a cross-host sharding is illegal — each process
    instead contributes its addressable shards via make_array_from_callback
    (every process holds the identical full value, so shards agree)."""
    if jax.process_count() > 1:
        arr = np.asarray(value)
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])
    return jax.device_put(value, sharding)


def _mh_zeros(shape, dtype, sharding):
    """Sharded zeros that never materialize on one host: compiled creation
    places each shard directly on its device, which is both multi-host-legal
    and HBM-friendly for multi-GB KV pools."""
    if jax.process_count() > 1:
        # jit is the only multi-host-legal way to get out_shardings placement.
        # dtpu: ignore[jit-recompile-hazard, unregistered-jit] until=2027-08-01 -- one-shot at pool creation, never dispatched from the serving loop
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=sharding)()
    return jax.device_put(jnp.zeros(shape, dtype), sharding)


class ModelRunner:
    def __init__(self, config: EngineConfig, params=None,
                 devices: list | None = None, seed: int = 0):
        self.config = config
        spec = config.model
        # KV-pool quantization (engine/kv_quant.py): resolved ONCE here —
        # pool sizing, allocation, parcels and the HBM ledger all key off
        # this field.
        self.quant_kv = config.resolve_quant_kv()
        if self.quant_kv not in (None, "int8"):
            raise ValueError(
                f"quant_kv must be None or 'int8', got {self.quant_kv!r}")
        # TP feasibility + KV-head replication (the role of vLLM's KV-head
        # replication for tp > num_kv_heads): each canonical KV head is
        # duplicated tp/nkv times so the cache's head axis shards evenly
        # over "tp". q head j maps to effective group j // (H/tp), which
        # composes back to the canonical grouping j // (H/nkv).
        self.canonical_spec = spec
        self.canonical_nkv = spec.num_kv_heads
        if spec.num_heads % config.tp != 0:
            raise ValueError(
                f"num_heads={spec.num_heads} not divisible by tp={config.tp}")
        if config.tp > spec.num_kv_heads:
            if config.tp % spec.num_kv_heads != 0:
                raise ValueError(
                    f"tp={config.tp} exceeds num_kv_heads="
                    f"{spec.num_kv_heads} and is not a multiple of it; "
                    f"KV-head replication needs tp % num_kv_heads == 0")
            self.kv_rep = config.tp // spec.num_kv_heads
            spec = dataclasses.replace(spec, num_kv_heads=config.tp)
            log.info("tp=%d > num_kv_heads=%d: replicating each KV head "
                     "%dx (KV cache grows %dx)", config.tp,
                     self.canonical_nkv, self.kv_rep, self.kv_rep)
        else:
            if spec.num_kv_heads % config.tp != 0:
                raise ValueError(
                    f"num_kv_heads={spec.num_kv_heads} not divisible by "
                    f"tp={config.tp}")
            self.kv_rep = 1
        if spec.num_layers % config.pp != 0:
            raise ValueError(
                f"num_layers={spec.num_layers} not divisible by "
                f"pp={config.pp}")
        if spec.num_experts and spec.num_experts % config.tp != 0:
            raise ValueError(
                f"num_experts={spec.num_experts} not divisible by "
                f"tp={config.tp} (expert parallelism shards experts "
                f"over tp)")
        if config.sp > 1 and any(b % config.sp != 0
                                 for b in config.prefill_buckets):
            raise ValueError(
                f"sp={config.sp}: every prefill bucket "
                f"({config.prefill_buckets}) must be divisible by sp")
        self.spec = spec
        devices = devices if devices is not None else jax.devices()
        total = config.dp * config.pp * config.sp * config.tp
        if len(devices) < total:
            raise ValueError(f"need {total} devices, have {len(devices)}")
        dev_array = np.array(devices[:total]).reshape(
            config.dp, config.pp, config.sp, config.tp)
        self.mesh = Mesh(dev_array, ("dp", "pp", "sp", "tp"))
        # Auto-size from an ADDRESSABLE device: in multi-controller mode
        # devices[0] may belong to another process, and memory_stats on a
        # remote device fails into the conservative fallback.
        local = [d for d in devices[:total]
                 if d.process_index == jax.process_index()]
        self._sized_pages(local[0] if local else devices[0])

        # Shard or init parameters.
        pspecs = param_specs(spec)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P))
        if params is None:
            key = jax.random.key(seed)
            # local_devices, not devices: in multi-controller mode the
            # global cpu list starts with rank 0's device, and arrays
            # initialized onto a non-addressable device can't be read.
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                # Init the CANONICAL shape so tp variants of one logical
                # model share identical parameters.
                params = init_params(self.canonical_spec, key)
        if self.kv_rep > 1:
            if _already_quantized(params):
                raise ValueError(
                    "tp > num_kv_heads needs KV-head replication, which "
                    "rewrites bf16 wk/wv — pass unquantized params (the "
                    "runner quantizes after replication)")
            params = _replicate_kv_heads(params, self.canonical_spec,
                                         self.kv_rep)
        if spec.quant == "int8" and not _already_quantized(params):
            # Weight-only int8 (engine/quant.py): quantize on host AFTER
            # KV-head replication (which rewrites bf16 wk/wv), BEFORE the
            # sharded upload — HBM holds int8 + scales only.
            from dynamo_tpu.engine.quant import quantize_params
            params = quantize_params(params)
        self.params = jax.tree.map(_mh_put, params, shardings)

        # KV cache arrays [L, Nkv, P, page, D]: layers sharded over pp
        # (pages live with their layer's stage), kv heads over tp, and
        # [page, D] contiguous per (head, page) for clean Pallas DMAs.
        kv_spec = P("pp", "tp", None, None, None)
        self.kv_sharding = NamedSharding(self.mesh, kv_spec)
        kv_shape = (spec.num_layers, spec.num_kv_heads, self.num_pages,
                    config.page_size, spec.head_dim)
        if self.quant_kv == "int8":
            # int8 pages + per-token-per-head f32 scales (zero-init: an
            # unwritten page dequantizes to 0, same as the bf16 pool;
            # every real write goes through kv_quantize, whose scales
            # are never 0).
            scale_sharding = NamedSharding(self.mesh,
                                           P("pp", "tp", None, None))
            self.k_cache = QuantKV(
                _mh_zeros(kv_shape, jnp.int8, self.kv_sharding),
                _mh_zeros(kv_shape[:-1], jnp.float32, scale_sharding))
            self.v_cache = QuantKV(
                _mh_zeros(kv_shape, jnp.int8, self.kv_sharding),
                _mh_zeros(kv_shape[:-1], jnp.float32, scale_sharding))
        else:
            self.k_cache = _mh_zeros(kv_shape, jnp.bfloat16,
                                     self.kv_sharding)
            self.v_cache = _mh_zeros(kv_shape, jnp.bfloat16,
                                     self.kv_sharding)
        # Byte ledgers for the perf plane's HBM breakdown (/debug/perf):
        # this process's per-device share of params and the KV pool —
        # workspace is whatever memory_stats says is in use beyond them.
        # The KV ledger reports the ACTUAL pool dtype bytes (int8 + scale
        # vs bf16), so workspace attribution never silently absorbs the
        # quantization savings.
        per_weight = 1 if spec.quant == "int8" else 2
        shard = max(1, config.tp * config.pp)
        self.param_bytes = spec.num_params() * per_weight // shard
        self.kv_pool_bytes = (
            2 * self.num_pages * config.page_size
            * self._kv_token_head_bytes() * spec.num_layers
            * spec.num_kv_heads) // shard

        self._prefill_cache: dict = {}
        self._decode_fn = None
        self._window_cache: dict = {}
        # COMMITTED rng: an uncommitted key traces a different jit
        # signature than the committed key the program returns, so every
        # program family paid one duplicate XLA compile on its second
        # call (found by the perf plane's unexpected-recompile detector;
        # multi-controller mode keeps the host value — device_put onto a
        # cross-host sharding is illegal there, and followers replay
        # identical dispatches anyway).
        rng = jax.random.key(seed + 1)
        if jax.process_count() == 1:
            rng = jax.device_put(rng, NamedSharding(self.mesh, P()))
        self._rng = rng
        self.tokens_dev = _mh_zeros(
            (config.max_num_seqs,), jnp.int32,
            NamedSharding(self.mesh, P()))
        # Speculative decoding (config.spec_decode="ngram"): the full
        # per-slot token history rides ON DEVICE — hist_dev feeds the
        # in-graph n-gram draft lookup, positions_dev chains the
        # DATA-DEPENDENT sequence position between pipelined spec
        # windows (the host can't know how many drafts were accepted in
        # a window it hasn't processed yet, so device state is the only
        # correct source). Allocated lazily: plain serving never pays.
        self.hist_dev = None
        self.positions_dev = None
        if config.spec_decode:
            hist_w = config.max_pages_per_seq * config.page_size
            self.hist_dev = _mh_zeros(
                (config.max_num_seqs, hist_w), jnp.int32,
                NamedSharding(self.mesh, P()))
            self.positions_dev = _mh_zeros(
                (config.max_num_seqs,), jnp.int32,
                NamedSharding(self.mesh, P()))
        self._seed_hist_cache: dict = {}
        # Blocking prefill readbacks performed (slots=None fetch path).
        # The scheduled chunk path must never bump this: intermediate
        # chunks dispatch with no host readback at all (tests assert 0).
        self.sync_prefill_fetches = 0
        # Per-slot generated-token counts [slots, vocab] for OpenAI
        # frequency/presence penalties (vLLM semantics: output tokens
        # only). uint8 with saturation at 255; read ONLY by the penalized
        # window variant, so unpenalized serving never touches it.
        self.counts_dev = _mh_zeros(
            (config.max_num_seqs, spec.vocab_size), jnp.uint8,
            NamedSharding(self.mesh, P()))
        # Batched LoRA stacks (engine/lora.py): one pair of stacked
        # pytrees per target projection — A [L, S, d_in, r] /
        # B [L, S, r, d_out], S = max_adapters + 1 slots with slot 0 the
        # base model (all-zero, exact no-op). Layer-major so the layer
        # scan consumes them as xs alongside params["layers"]; the layer
        # axis shards over "pp" (stacks live with their stage), the rest
        # replicates — a rank-8 stack is megabytes, not gigabytes. The
        # named-parameter-overlay shape: adapter weights ride the mesh
        # beside base params and hot-swap per slot without touching them.
        self.lora = None
        if config.max_adapters > 0:
            S = config.max_adapters + 1
            r = config.lora_max_rank
            lspec = NamedSharding(self.mesh, P("pp", None, None, None))
            shapes = config.lora_target_shapes()
            if self.kv_rep > 1:
                # KV-head replication rewrote wk/wv: the B stacks' output
                # axis follows the EFFECTIVE head count (uploads
                # replicate columns in set_adapter_slot).
                dkv = spec.num_kv_heads * spec.head_dim
                shapes["wk"] = (shapes["wk"][0], dkv)
                shapes["wv"] = (shapes["wv"][0], dkv)
            L = spec.num_layers
            self.lora = {
                key: {"a": _mh_zeros((L, S, d_in, r), jnp.bfloat16, lspec),
                      "b": _mh_zeros((L, S, r, d_out), jnp.bfloat16, lspec)}
                for key, (d_in, d_out) in shapes.items()}
        self._attention_impl, self._window_attention_impl = \
            self._pick_attention()

    # -- setup ---------------------------------------------------------------
    def _kv_token_head_bytes(self) -> int:
        """Pool bytes per (layer, kv-head, token): bf16 values, or int8
        values + the f32 scale (engine/kv_quant.py)."""
        d = self.spec.head_dim
        return (d + KV_SCALE_BYTES) if self.quant_kv == "int8" else 2 * d

    def _sized_pages(self, device) -> None:
        cfg = self.config
        if cfg.num_pages is not None:
            self.num_pages = cfg.num_pages
            return
        # Size the KV pool from free HBM after params (reference: engines'
        # gpu_memory_utilization; here hbm_kv_budget_frac).
        try:
            stats = device.memory_stats()
            free = stats["bytes_limit"] - stats["bytes_in_use"]
        except Exception:  # noqa: BLE001 — CPU tests have no memory_stats
            free = 2 << 30
        # Params shard over tp and pp only (dp replicates them).
        per_weight = 1 if self.spec.quant == "int8" else 2
        param_bytes = (self.spec.num_params() * per_weight
                       // max(1, cfg.tp * cfg.pp))
        budget = max(64 << 20, int((free - param_bytes) * cfg.hbm_kv_budget_frac))
        # The cache shards over tp (heads) AND pp (layers). int8 pages
        # (+ scales) cost ~half the bf16 bytes, so the same budget holds
        # ~2x pages — directly more resident sequences per chip.
        token_bytes = (2 * self.spec.num_layers * self.spec.num_kv_heads
                       * self._kv_token_head_bytes())
        page_bytes = token_bytes * cfg.page_size // max(1, cfg.tp * cfg.pp)
        self.num_pages = max(16, budget // max(1, page_bytes))
        log.info("KV pool: %d pages of %d tokens (%.1f GiB)", self.num_pages,
                 cfg.page_size, self.num_pages * page_bytes / (1 << 30))

    def _pick_attention(self):
        """Returns (single-step impl, window impl)."""
        from dynamo_tpu.engine.model import paged_window_attention_xla
        backend = self.config.attention_backend
        if backend == "auto":
            # The bucketed XLA gather is the default. Measured on v5e
            # (qwen2.5-0.5b, bs32, M=16 windows, end-to-end decode_window
            # incl. readback — scripts/profile_decode.py): uniform-length
            # batches favor xla; the Pallas kernel wins only the
            # mixed-length case its design targets, within run noise, so
            # it stays opt-in. Correctness is CI-tested either way
            # (tests/test_attention_pallas.py, CPU interpret + TPU).
            backend = "xla"
        if backend == "pallas":
            d = self.spec.head_dim
            page = self.config.page_size
            packable = (d == 128
                        or (d < 128 and 128 % d == 0
                            and (page * d) % 128 == 0))
            if not packable:
                # The kernel packs D<128 rows into 128 lanes; that needs
                # 128 % D == 0 and page_size*D % 128 == 0.
                log.info("head_dim %d/page %d not packable to 128 lanes; "
                         "pallas kernel disabled", d, page)
                return paged_decode_attention_xla, paged_window_attention_xla
            try:
                from dynamo_tpu.engine.attention import (
                    paged_decode_attention_pallas,
                    paged_window_attention_pallas)
                return (paged_decode_attention_pallas,
                        paged_window_attention_pallas)
            except Exception:  # noqa: BLE001
                log.exception("pallas attention unavailable; using xla")
        return paged_decode_attention_xla, paged_window_attention_xla

    # -- compiled steps -------------------------------------------------------
    def _get_prefill(self, bucket: int, batch: int, with_history: bool,
                     penalized: bool = False, seeded: bool = False,
                     with_embeds: bool = False):
        key = (bucket, batch, with_history, penalized, seeded, with_embeds)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        page = self.config.page_size
        bucket_pages = bucket // page
        if with_history and self.config.sp > 1 \
                and self.config.ring_attention \
                and not getattr(self, "_ring_hist_warned", False):
            # History chunks (prompts longer than one prefill bucket)
            # read prior pages via the paged gather — that path still
            # uses the GSPMD all-gather, so ring attention covers
            # single-bucket prefills only. Warn at program-build time,
            # NOT inside the traced body: a trace-time branch runs once
            # per compile (impure-jit-program).
            self._ring_hist_warned = True
            log.info("ring attention: history-chunk prefill uses the "
                     "all-gather sp path (ring covers single-bucket "
                     "prefills)")

        # All host inputs travel in ONE packed int32 array (floats bitcast):
        # h2d transfers are latency-bound, so one transfer beats ten.
        # Columns: 0 start_pos, 1 n_tokens, 2 hist_len, 3 temp bits,
        # 4 top_k, 5 top_p bits, 6 logprobs flag, 7/8 penalty bits,
        # 9 seed, 10 seeded flag, 11 spare, then tokens[bucket],
        # ptab[bucket_pages], htab[maxp if with_history].
        # The penalized variant (preemption-recompute of a penalized
        # request) additionally reads prior-generation counts so even the
        # re-sampled token respects the penalties. The embeds variant
        # (multimodal prompts) takes encoder embeddings + a mask that
        # override the token table under media spans.
        def step(params, k_cache, v_cache, packed, rng, counts=None,
                 emb=None, emb_mask=None, lora=None):
            start = packed[:, 0]
            n = packed[:, 1]
            hist_lens = packed[:, 2]
            temp = jax.lax.bitcast_convert_type(packed[:, 3], jnp.float32)
            top_k = packed[:, 4]
            top_p = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
            adapter_ids = packed[:, 11]
            tokens = packed[:, _PF_HDR:_PF_HDR + bucket]
            page_table = packed[:, _PF_HDR + bucket:
                                _PF_HDR + bucket + bucket_pages]
            hist_table = packed[:, _PF_HDR + bucket + bucket_pages:]
            # positions: start..start+n-1, pads clamped to the last valid.
            positions = start[:, None] + jnp.minimum(
                jnp.arange(bucket)[None, :],
                jnp.maximum(n - 1, 0)[:, None])
            seq_lens = n
            sp_shard = self.config.sp > 1
            cfg_pp = self.config.pp
            pipelined = (not with_history and cfg_pp > 1
                         and self.config.pp_microbatch and not sp_shard
                         and not with_embeds and lora is None
                         and batch % cfg_pp == 0
                         and spec.num_layers % cfg_pp == 0)
            if with_history:
                logits, k_cache, v_cache = _prefill_with_history(
                    params, spec, k_cache, v_cache, tokens, positions,
                    page_table, seq_lens, hist_table, hist_lens,
                    self._attention_impl, sp_shard=sp_shard,
                    x_embeds=emb, embeds_mask=emb_mask,
                    lora=lora, adapter_ids=adapter_ids)
            elif pipelined:
                from dynamo_tpu.engine.model import (
                    prefill_forward_pipelined)
                logits, k_cache, v_cache = prefill_forward_pipelined(
                    params, spec, k_cache, v_cache, tokens, positions,
                    page_table, seq_lens, n_stages=cfg_pp)
            else:
                logits, k_cache, v_cache = prefill_forward(
                    params, spec, k_cache, v_cache, tokens, positions,
                    page_table, seq_lens, sp_shard=sp_shard,
                    ring_mesh=(self.mesh if sp_shard
                               and self.config.ring_attention else None),
                    x_embeds=emb, embeds_mask=emb_mask,
                    lora=lora, adapter_ids=adapter_ids)
            if penalized:
                freq = jax.lax.bitcast_convert_type(packed[:, 7],
                                                    jnp.float32)
                pres = jax.lax.bitcast_convert_type(packed[:, 8],
                                                    jnp.float32)
                cf = counts.astype(jnp.float32)
                logits = (logits - freq[:, None] * cf
                          - pres[:, None] * (cf > 0))
            rng, sub = jax.random.split(rng)
            if seeded:
                # First generated token lands at position start + n.
                seed_flag = packed[:, 10] > 0
                base_keys = jax.vmap(jax.random.key)(packed[:, 9])
                per_seed = jax.vmap(jax.random.fold_in)(base_keys, start + n)
                shared = jax.random.split(sub, temp.shape[0])
                row_keys = jax.random.wrap_key_data(jnp.where(
                    seed_flag[:, None],
                    jax.random.key_data(per_seed),
                    jax.random.key_data(shared)))
                sampled = sample_tokens_per_row(logits, temp, top_k, top_p,
                                                row_keys)
            else:
                sampled = sample_tokens(logits, temp, top_k, top_p, sub)
            B = sampled.shape[0]
            lp, top_v, top_i = jax.lax.cond(
                jnp.any(packed[:, 6] > 0),
                lambda _: _logprobs_of(logits, sampled),
                lambda _: (jnp.zeros((B,), jnp.float32),
                           jnp.zeros((B, TOP_LOGPROBS), jnp.float32),
                           jnp.zeros((B, TOP_LOGPROBS), jnp.int32)),
                None)
            return sampled, lp, top_v, top_i, logits, k_cache, v_cache, rng

        fn = perf.instrumented_jit("prefill", step, key=key,
                                   donate_argnums=(1, 2))
        self._prefill_cache[key] = fn
        return fn

    def _get_decode(self):
        if self._decode_fn is not None:
            return self._decode_fn
        spec = self.spec

        def step(params, k_cache, v_cache, tokens, positions, page_table,
                 seq_lens, temperature, top_k, top_p, rng):
            logits, k_cache, v_cache = decode_forward(
                params, spec, k_cache, v_cache, tokens, positions,
                page_table, seq_lens, attention_impl=self._attention_impl)
            rng, sub = jax.random.split(rng)
            sampled = sample_tokens(logits, temperature, top_k, top_p, sub)
            return sampled, k_cache, v_cache, rng

        self._decode_fn = perf.instrumented_jit(
            "decode_step", step, key="decode_step", donate_argnums=(1, 2))
        return self._decode_fn

    def _get_window(self, window: int, bucket_pages: int,
                    penalized: bool = False, seeded: bool = False):
        """Window program, specialized on ``penalized`` and ``seeded``:
        the penalty variant threads the [B, V] counts state through the
        scan; the seeded variant derives each slot's PRNG key from
        (seed, token position), making a seeded request's draws
        batch-invariant and preemption-stable. The common variant is the
        exact plain program, so default serving costs nothing extra."""
        key = (window, bucket_pages, penalized, seeded)
        fn = self._window_cache.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        page = self.config.page_size

        def run_window(params, k_cache, v_cache, tokens_dev, packed, rng,
                       counts=None, lora=None):
            adapter_ids = packed[:, PK_ADAPTER]
            mask = packed[:, PK_OVERRIDE] > 0
            tokens0 = jnp.where(mask, packed[:, PK_TOKEN], tokens_dev)
            positions0 = packed[:, PK_POS]
            seq_lens0 = packed[:, PK_SEQLEN]
            top_k = packed[:, PK_TOPK]
            temp = jax.lax.bitcast_convert_type(packed[:, PK_TEMP],
                                                jnp.float32)
            top_p = jax.lax.bitcast_convert_type(packed[:, PK_TOPP],
                                                 jnp.float32)
            cap = packed[:, PK_CAP]
            freq_pen = jax.lax.bitcast_convert_type(packed[:, PK_FREQPEN],
                                                    jnp.float32)
            pres_pen = jax.lax.bitcast_convert_type(packed[:, PK_PRESPEN],
                                                    jnp.float32)
            if seeded:
                seed_flag = packed[:, PK_SEEDED] > 0
                base_keys = jax.vmap(jax.random.key)(packed[:, PK_SEED])
            page_table = packed[:, PK_PREFIX:]
            B = tokens0.shape[0]
            L, nkv = spec.num_layers, spec.num_kv_heads
            d = spec.head_dim
            # Cache-resident history length is FIXED across the window: the
            # window's own tokens live in a small in-window buffer and are
            # committed to the pool by ONE scatter at the end. The caches
            # are read-only inside the scan — carrying a multi-GB pool
            # through scan ys/carries makes XLA copy it per step (measured:
            # 50 ms/step at a 3 GB pool, vs flat ~1.5 ms this way).
            hist_lens = jnp.maximum(seq_lens0 - 1, 0)
            kbuf0 = jnp.zeros((L, nkv, B, window, d), k_cache.dtype)
            vbuf0 = jnp.zeros((L, nkv, B, window, d), v_cache.dtype)

            want_lp = jnp.any(packed[:, PK_LOGPROB] > 0)

            def step(carry, m):
                tokens, positions, kbuf, vbuf, rng, cnts = carry
                # A slot advances only while live AND within its allocated
                # pages; at capacity it freezes in-graph (the host emits
                # LENGTH when it sees the cap).
                live = (seq_lens0 > 0) & (positions < cap)
                logits, k_new, v_new = decode_window_step(
                    params, spec, k_cache, v_cache, kbuf, vbuf, m, tokens,
                    positions, page_table, hist_lens,
                    attention_impl=self._window_attention_impl,
                    lora=lora, adapter_ids=adapter_ids)
                # Append this step's K/V ([L,B,Nkv,D] -> window col m).
                kbuf = jax.lax.dynamic_update_slice(
                    kbuf, k_new.transpose(0, 2, 1, 3)[:, :, :, None],
                    (0, 0, 0, m, 0))
                vbuf = jax.lax.dynamic_update_slice(
                    vbuf, v_new.transpose(0, 2, 1, 3)[:, :, :, None],
                    (0, 0, 0, m, 0))
                if penalized:
                    # OpenAI penalties over generated tokens (vLLM
                    # semantics): subtract before temperature/top-k.
                    cf = cnts.astype(jnp.float32)
                    logits = (logits - freq_pen[:, None] * cf
                              - pres_pen[:, None] * (cf > 0))
                rng, sub = jax.random.split(rng)
                if seeded:
                    # The token being sampled lands at positions + 1: fold
                    # the request seed with that absolute position, so the
                    # draw depends only on (seed, position, logits).
                    per_seed = jax.vmap(jax.random.fold_in)(
                        base_keys, positions + 1)
                    shared = jax.random.split(sub, temp.shape[0])
                    row_keys = jax.random.wrap_key_data(jnp.where(
                        seed_flag[:, None],
                        jax.random.key_data(per_seed),
                        jax.random.key_data(shared)))
                    sampled = sample_tokens_per_row(logits, temp, top_k,
                                                    top_p, row_keys)
                else:
                    sampled = sample_tokens(logits, temp, top_k, top_p, sub)
                B = sampled.shape[0]
                if penalized:
                    # Saturating per-row count bump for this step's token.
                    b_idx = jnp.arange(B)
                    cur = cnts[b_idx, sampled]
                    inc = (live & (cur < 255)).astype(jnp.uint8)
                    cnts = cnts.at[b_idx, sampled].add(inc)
                # Logprobs only when some slot asked (lax.cond executes one
                # branch on TPU: zero cost otherwise).
                lp, top_v, top_i = jax.lax.cond(
                    want_lp,
                    lambda _: _logprobs_of(logits, sampled),
                    lambda _: (jnp.zeros((B,), jnp.float32),
                               jnp.zeros((B, TOP_LOGPROBS), jnp.float32),
                               jnp.zeros((B, TOP_LOGPROBS), jnp.int32)),
                    None)
                tokens = jnp.where(live, sampled, tokens)
                positions = positions + live.astype(jnp.int32)
                return (tokens, positions, kbuf, vbuf, rng, cnts), (
                    sampled, lp, top_v, top_i)

            carry0 = (tokens0, positions0, kbuf0, vbuf0, rng,
                      counts if penalized else jnp.zeros((), jnp.uint8))
            (tokens, _, kbuf, vbuf, rng, counts_out), \
                (toks, lps, top_vs, top_is) = \
                jax.lax.scan(step, carry0, jnp.arange(window))
            # Commit the window: scatter every (slot, step) entry into its
            # page. Frozen/inactive entries land on the scratch page 0.
            m_idx = jnp.arange(window)[:, None]                      # [M,1]
            adv = jnp.clip(jnp.minimum(m_idx, cap[None, :] - positions0),
                           0, None)
            pos_m = positions0[None, :] + adv                        # [M,B]
            live_m = (seq_lens0[None, :] > 0) & (pos_m < cap[None, :])
            pidx = jnp.clip(pos_m // page, 0, page_table.shape[1] - 1)
            dest = jnp.take_along_axis(
                jnp.broadcast_to(page_table[None], (window, *page_table.shape)),
                pidx[:, :, None], axis=2)[:, :, 0]                   # [M,B]
            dest = jnp.where(live_m, dest, 0)
            off = jnp.where(live_m, pos_m % page, 0)
            # kbuf [L,Nkv,B,M,D] -> [L,Nkv,M,B,D] matching index arrays.
            # scatter_tokens quantizes int8 pools inside the same commit.
            k_cache = scatter_tokens(k_cache, kbuf.transpose(0, 1, 3, 2, 4),
                                     dest, off)
            v_cache = scatter_tokens(v_cache, vbuf.transpose(0, 1, 3, 2, 4),
                                     dest, off)
            if penalized:
                return (toks, lps, top_vs, top_is, tokens, k_cache,
                        v_cache, rng, counts_out)
            return toks, lps, top_vs, top_is, tokens, k_cache, v_cache, rng

        donate = (1, 2, 6) if penalized else (1, 2)
        fn = perf.instrumented_jit("decode_window", run_window, key=key,
                                   donate_argnums=donate)
        self._window_cache[key] = fn
        return fn

    def _get_spec_window(self, m_outer: int, k: int, bucket_pages: int):
        """Speculative window program: m_outer verify steps, each
        drafting up to ``k`` tokens by bigram prompt-lookup against the
        ON-DEVICE token history and verifying them in one forward
        (model.decode_window_multi_step). Sequence position is carried in
        positions_dev between windows — the advance is data-dependent
        (accepted drafts), so pipelined dispatches must chain on-device.

        Sampling is on-device rejection sampling degenerated for the
        point-mass (n-gram) drafter: accepting a draft w.p.
        min(1, p_target/q_draft) and resampling the first rejection from
        the normalized residual collapses, when q is a point mass at the
        draft token, to "sample x ~ target at each position; accept iff
        x == draft; emit x either way" — so each verify position draws
        ONE per-row sample from the target distribution and the existing
        prefix-acceptance compare is the accept rule. Every emitted
        token is exactly target-distributed; greedy rows (temp <= 0)
        degenerate to argmax, bit-identical to non-spec greedy decode.
        Temperature/top-k/top-p/seed ride in as DATA (packed columns):
        one program serves any mix, zero recompiles. Seeded rows fold
        the request seed with the token's absolute landing position —
        the same convention as the plain seeded window — so a seeded
        stream is token-identical with spec on or off."""
        key = ("spec", m_outer, k, bucket_pages)
        fn = self._window_cache.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        page = self.config.page_size
        S = k + 1
        W = m_outer * S  # in-window KV columns (worst case: all accepted)

        def run_spec(params, k_cache, v_cache, tokens_dev, hist_dev,
                     positions_dev, packed, rng, lora=None):
            from dynamo_tpu.engine.model import decode_window_multi_step
            adapter_ids = packed[:, PK_ADAPTER]
            override = packed[:, PK_OVERRIDE] > 0
            tokens0 = jnp.where(override, packed[:, PK_TOKEN], tokens_dev)
            pos0 = jnp.where(override, packed[:, PK_POS], positions_dev)
            active = packed[:, PK_SEQLEN] > 0
            cap = packed[:, PK_CAP]
            top_k = packed[:, PK_TOPK]
            temp = jax.lax.bitcast_convert_type(packed[:, PK_TEMP],
                                                jnp.float32)
            top_p = jax.lax.bitcast_convert_type(packed[:, PK_TOPP],
                                                 jnp.float32)
            seed_flag = packed[:, PK_SEEDED] > 0
            base_keys = jax.vmap(jax.random.key)(packed[:, PK_SEED])
            page_table = packed[:, PK_PREFIX:]
            B = tokens0.shape[0]
            H = hist_dev.shape[1]
            L, nkv, d = spec.num_layers, spec.num_kv_heads, spec.head_dim
            b_idx = jnp.arange(B)
            kbuf0 = jnp.zeros((L, nkv, B, W, d), k_cache.dtype)
            vbuf0 = jnp.zeros((L, nkv, B, W, d), v_cache.dtype)
            # Per-(row, verify-column) sampling params: column j of a
            # row's block shares that row's temperature/top-k/top-p.
            temp_s = jnp.repeat(temp, S)
            top_k_s = jnp.repeat(top_k, S)
            top_p_s = jnp.repeat(top_p, S)
            seed_s = jnp.repeat(seed_flag, S)
            base_s = jax.random.wrap_key_data(
                jnp.repeat(jax.random.key_data(base_keys), S, axis=0))

            def step(carry, _):
                tokens, pos, wlen, hist, kbuf, vbuf, rng = carry
                live = active & (pos < cap)
                safe_pos = jnp.clip(pos, 0, H - 1)
                # Invariant: hist[pos] = the token being fed this step.
                hist = hist.at[b_idx, safe_pos].set(
                    jnp.where(live, tokens, hist[b_idx, safe_pos]))
                # Bigram prompt-lookup: most recent earlier occurrence of
                # (hist[pos-1], tokens); drafts = what followed it.
                x1 = hist[b_idx, jnp.clip(pos - 1, 0, H - 1)]
                jidx = jnp.arange(H - 1)
                match = ((hist[:, :-1] == x1[:, None])
                         & (hist[:, 1:] == tokens[:, None])
                         & (jidx[None, :] + 1 < pos[:, None]))
                jstar = jnp.max(jnp.where(match, jidx[None, :], -1), axis=1)
                found = (jstar >= 0) & (pos >= 1) & live
                didx = jstar[:, None] + 2 + jnp.arange(k)[None, :]  # [B,k]
                drafts = hist[b_idx[:, None], jnp.clip(didx, 0, H - 1)]
                dvalid = (found[:, None]
                          & (didx <= pos[:, None])
                          & (pos[:, None] + 1 + jnp.arange(k)[None, :]
                             < cap[:, None]))
                # Draft validity must be a prefix (cumulative AND).
                dvalid = jnp.cumprod(
                    dvalid.astype(jnp.int32), axis=1).astype(bool)
                ndraft = dvalid.sum(axis=1)
                tok_blk = jnp.concatenate(
                    [tokens[:, None], jnp.where(dvalid, drafts, 0)], axis=1)
                pos_blk = pos[:, None] + jnp.arange(S)[None, :]
                # Cache-resident history is FIXED across the window
                # (pos0): everything this window produced lives in
                # kbuf/vbuf cols < wlen, and the pool pages for those
                # positions hold garbage until the post-scan commit.
                logits, k_new, v_new = decode_window_multi_step(
                    params, spec, k_cache, v_cache, kbuf, vbuf, wlen,
                    tok_blk, pos_blk, page_table, hist_lens=pos0,
                    lora=lora, adapter_ids=adapter_ids)
                # One target-distributed draw per verify position ([B,S]
                # flattened to [B*S] rows — the sampler's per-row core is
                # shared with the plain decode window). Column j's token
                # LANDS at pos + 1 + j: seeded rows fold the request seed
                # with that absolute position (the plain seeded window's
                # exact convention), unseeded rows draw fresh split keys.
                rng, sub = jax.random.split(rng)
                land = (pos[:, None] + 1
                        + jnp.arange(S)[None, :]).reshape(-1)  # [B*S]
                per_seed = jax.vmap(jax.random.fold_in)(base_s, land)
                shared = jax.random.split(sub, B * S)
                row_keys = jax.random.wrap_key_data(jnp.where(
                    seed_s[:, None],
                    jax.random.key_data(per_seed),
                    jax.random.key_data(shared)))
                out = sample_tokens_per_row(
                    logits.reshape(B * S, -1), temp_s, top_k_s, top_p_s,
                    row_keys).reshape(B, S)
                # Prefix-acceptance IS the rejection-sampling accept rule
                # for a point-mass drafter: out[:, j] ~ target, accepted
                # iff it reproduced the draft; the first rejection's draw
                # is the residual resample (emitted via out[b, a]); draws
                # past it are conditioned on a dead prefix and dropped.
                eq = (drafts == out[:, :k]) & dvalid
                accflags = jnp.cumprod(
                    eq.astype(jnp.int32), axis=1).astype(bool)
                a = accflags.sum(axis=1)              # accepted drafts
                e = jnp.where(live, a + 1, 0)         # emitted / advance
                # Commit t0 + accepted drafts (block cols < e) into the
                # window buffer at cols wlen..wlen+e-1; invalid -> W
                # (dropped). k_new [L,B,S,Nkv,D] -> kbuf [L,Nkv,B,W,D].
                cols = wlen[:, None] + jnp.arange(S)[None, :]
                kvvalid = jnp.arange(S)[None, :] < e[:, None]
                cols = jnp.where(kvvalid, cols, W)
                kn = k_new.transpose(0, 3, 1, 2, 4)   # [L,Nkv,B,S,D]
                vn = v_new.transpose(0, 3, 1, 2, 4)
                kbuf = kbuf.at[:, :, b_idx[:, None], cols].set(
                    kn, mode="drop")
                vbuf = vbuf.at[:, :, b_idx[:, None], cols].set(
                    vn, mode="drop")
                # History gains every emitted token out[0..a] at pos+1+j.
                hidx = pos[:, None] + 1 + jnp.arange(S)[None, :]
                hidx = jnp.where(kvvalid & (hidx < H), hidx, H)
                hist = hist.at[b_idx[:, None], hidx].set(out, mode="drop")
                tokens = jnp.where(live, out[b_idx, a], tokens)
                pos = pos + e
                wlen = wlen + e
                # Emit e (not a): e == 0 distinguishes a frozen/inactive
                # slot from "zero drafts accepted" (e == 1) — the host
                # walk needs that to mirror the in-graph freeze.
                return (tokens, pos, wlen, hist, kbuf, vbuf, rng), (
                    out, e.astype(jnp.int32), ndraft.astype(jnp.int32))

            carry0 = (tokens0, pos0, jnp.zeros((B,), jnp.int32), hist_dev,
                      kbuf0, vbuf0, rng)
            (tokens, pos, wlen, hist, kbuf, vbuf, rng), \
                (outs, emits, ndrafts) = \
                jax.lax.scan(step, carry0, jnp.arange(m_outer))
            # Commit the window buffer: col c holds the token at absolute
            # position pos0 + c; cols >= wlen land on scratch page 0.
            c_idx = jnp.broadcast_to(jnp.arange(W)[None, :], (B, W))
            abspos = pos0[:, None] + c_idx
            valid = c_idx < wlen[:, None]
            pidx = jnp.clip(abspos // page, 0, page_table.shape[1] - 1)
            dest = jnp.take_along_axis(page_table, pidx, axis=1)
            dest = jnp.where(valid, dest, 0)
            off = jnp.where(valid, abspos % page, 0)
            k_cache = scatter_tokens(k_cache, kbuf, dest, off)
            v_cache = scatter_tokens(v_cache, vbuf, dest, off)
            return (outs, emits, ndrafts, tokens, pos, hist,
                    k_cache, v_cache, rng)

        fn = perf.instrumented_jit("spec_window", run_spec, key=key,
                                   donate_argnums=(1, 2, 4))
        self._window_cache[key] = fn
        return fn

    def decode_spec_window(self, packed: np.ndarray, m_outer: int, k: int):
        """Dispatch one speculative window (m_outer verify steps x up to
        k drafts each). Returns (toks [m_outer,B,k+1], accs [m_outer,B],
        ndrafts [m_outer,B]) device arrays; positions/tokens/history
        chain on-device (see _get_spec_window)."""
        bucket_pages = packed.shape[1] - PK_PREFIX
        fn = self._get_spec_window(m_outer, k, bucket_pages)
        kw = {} if self.lora is None else {"lora": self.lora}
        with self.mesh:
            (outs, accs, ndrafts, self.tokens_dev, self.positions_dev,
             self.hist_dev, self.k_cache, self.v_cache, self._rng) = fn(
                self.params, self.k_cache, self.v_cache, self.tokens_dev,
                self.hist_dev, self.positions_dev, jnp.asarray(packed),
                self._rng, **kw)
        return outs, accs, ndrafts

    def seed_history(self, entries: list[tuple]) -> None:
        """Scatter prefill-chunk tokens into the on-device history +
        position buffers (spec decode only; no-op otherwise). Entries:
        (slot, tokens_np, start_pos, final, first_token) — ``final``
        rows also record the chained sampled token (from tokens_dev,
        or ``first_token`` >= 0 for paths that know it host-side, e.g.
        KV-injected disagg decode) and set positions_dev."""
        if self.hist_dev is None or not entries:
            return
        n_max = max(len(t) for _, t, _, _, _ in entries)
        bucket = 64  # pow2 buckets; full prompts can exceed prefill buckets
        while bucket < n_max:
            bucket *= 2
        bp = 1
        while bp < len(entries):
            bp *= 2
        toks = np.zeros((bp, bucket), np.int32)
        meta = np.zeros((bp, 4), np.int32)  # slot, start, len, final_tok
        meta[:, 3] = -2  # inactive rows
        for i, (slot, t, start, final, first_tok) in enumerate(entries):
            toks[i, :len(t)] = t
            meta[i] = (slot, start, len(t),
                       (first_tok if final and first_tok is not None
                        else (-1 if final else -2)))
        key = ("seedh", bucket, bp)
        fn = self._seed_hist_cache.get(key)
        if fn is None:
            H = self.hist_dev.shape[1]

            def scatter(hist, pos_dev, tokens_dev, toks, meta):
                slots = meta[:, 0]
                starts = meta[:, 1]
                lens = meta[:, 2]
                ftok = meta[:, 3]
                idx = starts[:, None] + jnp.arange(bucket)[None, :]
                ok = ((jnp.arange(bucket)[None, :] < lens[:, None])
                      & (idx < H))  # padding rows have lens == 0
                idx = jnp.where(ok, idx, H)
                hist = hist.at[slots[:, None], idx].set(toks, mode="drop")
                # Final rows: the sampled token sits at start+len and
                # becomes the slot's next fed position. Non-final and
                # inactive rows scatter to dropped (out-of-range)
                # indices — duplicate in-range indices across rows would
                # have unspecified write order.
                final = ftok >= -1
                fpos = jnp.where(final, starts + lens, H)
                fval = jnp.where(ftok >= 0, ftok, tokens_dev[slots])
                hist = hist.at[slots, fpos].set(fval, mode="drop")
                pslot = jnp.where(final, slots, pos_dev.shape[0])
                pos_dev = pos_dev.at[pslot].set(starts + lens, mode="drop")
                return hist, pos_dev

            fn = perf.instrumented_jit("seed_history", scatter, key=key,
                                       donate_argnums=(0, 1))
            self._seed_hist_cache[key] = fn
        with self.mesh:
            self.hist_dev, self.positions_dev = fn(
                self.hist_dev, self.positions_dev, self.tokens_dev,
                jnp.asarray(toks), jnp.asarray(meta))

    # -- batched LoRA (engine/lora.py) ----------------------------------------
    def set_adapter_slot(self, slot: int, host: dict) -> None:
        """Upload one adapter's host weights into device slot ``slot``
        (ENGINE THREAD; the AdapterStore's hot-load path). ``host`` is
        the COMPLETE target set {key: (A [L, d_in, r], B [L, r, d_out])}
        at canonical shapes — untargeted projections are zeros, so a
        slot overwrite can never leave a previous tenant's deltas
        behind. One compiled scatter program for every slot (the slot
        index is data), registered through perf.instrumented_jit."""
        if self.lora is None:
            raise RuntimeError("runner built without max_adapters")
        if not 1 <= slot <= self.config.max_adapters:
            raise ValueError(f"adapter slot {slot} outside "
                             f"[1, {self.config.max_adapters}]")
        key = ("lora_load",)
        fn = self._window_cache.get(key)
        if fn is None:
            def scatter(lora, host, s):
                return jax.tree.map(
                    lambda dst, src: dst.at[:, s].set(src), lora, host)
            fn = perf.instrumented_jit("lora_load", scatter, key=key,
                                       donate_argnums=(0,))
            self._window_cache[key] = fn
        dev = {}
        for k, (a, b) in host.items():
            a = np.asarray(a)
            b = np.asarray(b)
            if self.kv_rep > 1 and k in ("wk", "wv"):
                # Match the replicated wk/wv columns: canonical head g's
                # B columns land at effective heads [g*rep, (g+1)*rep).
                L, r, _ = b.shape
                d = self.spec.head_dim
                b = (b.reshape(L, r, self.canonical_nkv, d)
                     .repeat(self.kv_rep, axis=2)
                     .reshape(L, r, self.spec.num_kv_heads * d))
            dev[k] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        with self.mesh:
            self.lora = fn(self.lora, dev, jnp.asarray(slot, jnp.int32))

    # -- public API (blocking; called from the engine thread) -----------------
    def prefill_batch(self, seqs: list[PrefillSeq],
                      slots: list[int] | None = None,
                      count_rows: np.ndarray | None = None,
                      fetch: bool = True):
        """Prefill a batch of chunks (same compiled program per
        (bucket, padded-batch, with_history) key).

        With ``slots=None`` (tests, disagg prefill): blocks and returns the
        sampled first tokens [len(seqs)] as numpy. With ``slots`` given
        (the serving engine): the sampled tokens are ALSO scattered into
        ``tokens_dev[slots]`` on-device — the decode windows chain from
        them with no override upload — and the DEVICE array is returned so
        the caller can fetch the values asynchronously (first-token
        emission never blocks the dispatch pipeline on a host<->device
        round trip).

        All rows must agree on with-history-ness; rows are padded to the next
        batch bucket (1,2,4,8) with inactive rows.
        """
        cfg = self.config
        page = cfg.page_size
        n_max = max(len(s.tokens) for s in seqs)
        bucket = cfg.bucket_for(n_max)
        bucket_pages = bucket // page
        with_history = any(s.hist_pages is not None and len(s.hist_pages)
                           for s in seqs)
        bp = 1
        while bp < len(seqs):
            bp *= 2
        maxp = cfg.max_pages_per_seq
        width = _PF_HDR + bucket + bucket_pages + (maxp if with_history else 0)
        packed = np.zeros((bp, width), np.int32)
        for i, s in enumerate(seqs):
            n = len(s.tokens)
            packed[i, 0] = s.start_pos
            packed[i, 1] = n
            temp, top_k, top_p = s.sampling
            packed[i, 3] = np.float32(temp).view(np.int32)
            packed[i, 4] = top_k
            packed[i, 5] = np.float32(top_p).view(np.int32)
            packed[i, 6] = int(s.logprobs)
            fp, pp = s.penalties
            packed[i, 7] = np.float32(fp).view(np.int32)
            packed[i, 8] = np.float32(pp).view(np.int32)
            if s.seed is not None:
                packed[i, 9] = mask_seed(s.seed)
                packed[i, 10] = 1
            packed[i, 11] = s.adapter_id
            packed[i, _PF_HDR:_PF_HDR + n] = s.tokens
            # Pad page-table rows stay 0 = the allocator's RESERVED scratch
            # page, so padded block scatters land there — padding with a
            # live page would create duplicate scatter indices whose XLA
            # write order is unspecified.
            packed[i, _PF_HDR + bucket:
                   _PF_HDR + bucket + len(s.chunk_pages)] = s.chunk_pages
            if with_history and s.hist_pages is not None and len(s.hist_pages):
                off = _PF_HDR + bucket + bucket_pages
                packed[i, off:off + len(s.hist_pages)] = s.hist_pages
                packed[i, 2] = s.start_pos
        penalized = count_rows is not None
        seeded = any(s.seed is not None for s in seqs)
        with_embeds = any(s.embeds is not None for s in seqs)
        kw = {}
        if with_embeds:
            import ml_dtypes
            emb = np.zeros((bp, bucket, self.spec.hidden_size),
                           ml_dtypes.bfloat16)
            emb_mask = np.zeros((bp, bucket), bool)
            for i, s in enumerate(seqs):
                if s.embeds is None:
                    continue
                n_row = len(s.tokens)
                emb[i, :n_row] = s.embeds.astype(ml_dtypes.bfloat16)
                emb_mask[i, :n_row] = s.embeds_mask
            kw = {"emb": jnp.asarray(emb), "emb_mask": jnp.asarray(emb_mask)}
        if self.lora is not None:
            # Adapter stacks ride every prefill when LoRA serving is on:
            # row ids are data (col 11), so one program covers every mix.
            kw["lora"] = self.lora
        fn = self._get_prefill(bucket, bp, with_history, penalized, seeded,
                               with_embeds)
        with self.mesh:
            if penalized:
                rows = np.asarray(count_rows, np.uint8)
                if rows.shape[0] < bp:  # pad to the batch bucket
                    rows = np.concatenate(
                        [rows, np.zeros((bp - rows.shape[0], rows.shape[1]),
                                        np.uint8)])
                (sampled, lp, top_v, top_i, logits, self.k_cache,
                 self.v_cache, self._rng) = fn(
                    self.params, self.k_cache, self.v_cache,
                    jnp.asarray(packed), self._rng, jnp.asarray(rows), **kw)
            else:
                (sampled, lp, top_v, top_i, logits, self.k_cache,
                 self.v_cache, self._rng) = fn(
                    self.params, self.k_cache, self.v_cache,
                    jnp.asarray(packed), self._rng, **kw)
        # Device handle (no transfer unless a caller converts it).
        self.last_prefill_logits = logits
        if slots is not None:
            idx = jnp.asarray(np.asarray(slots, np.int32))
            with self.mesh:
                self.tokens_dev = self.tokens_dev.at[idx].set(
                    sampled[:len(seqs)])
                if count_rows is not None:
                    # Penalty state for these slots: prior generated-token
                    # counts (zeros for fresh requests; rebuilt rows after
                    # preemption-recompute) plus this prefill's sampled
                    # token, which stays on device.
                    cnt = jnp.asarray(count_rows, jnp.uint8)
                    sel = sampled[:len(seqs)]
                    n = jnp.arange(len(seqs))
                    bumped = cnt.at[n, sel].add(
                        (cnt[n, sel] < 255).astype(jnp.uint8))
                    self.counts_dev = self.counts_dev.at[idx].set(bumped)
            for arr in (sampled, lp, top_v, top_i):
                try:
                    arr.copy_to_host_async()
                except Exception:  # noqa: BLE001
                    pass
            return {"tokens": sampled, "lp": lp, "top_v": top_v,
                    "top_i": top_i}
        if not fetch:
            # Dispatch-only (intermediate prefill chunks): the KV pages
            # are written on device and the sampled token is discarded.
            # Return the device array purely as a completion handle
            # (is_ready pacing) — no host copy is even started.
            return sampled
        self.sync_prefill_fetches += 1
        # dtpu: ignore[host-sync-in-hot-path] -- fetch=True branch only: prefill_chunk_async passes fetch=False and returns at the dispatch-only branch above (runtime twin: sync_prefill_fetches counter)
        return np.asarray(jax.device_get(sampled))[:len(seqs)]

    # dtpu: hotpath -- PR 5 zero-readback invariant, now static: no device->host fetch anywhere below this entry
    def prefill_chunk_async(self, seq: PrefillSeq):
        """Dispatch ONE intermediate prefill chunk with NO host readback
        (the stall-free chunked-prefill path): device-stream order
        guarantees the chunk's KV writes land before any later program
        reads them as history, so nothing about the chunk needs to come
        back to the host. Returns the sampled-token device array as a
        completion handle only."""
        return self.prefill_batch([seq], fetch=False)

    def prefill(self, tokens: np.ndarray, start_pos: int,
                chunk_pages: np.ndarray, hist_pages: np.ndarray | None,
                sampling: tuple[float, int, float],
                penalties: tuple[float, float] = (0.0, 0.0),
                count_row: np.ndarray | None = None,
                seed: int | None = None,
                embeds: np.ndarray | None = None,
                embeds_mask: np.ndarray | None = None
                ) -> tuple[int, jax.Array]:
        """Single-sequence prefill chunk; returns (sampled_token,
        last-position logits [1,V])."""
        seq = PrefillSeq(tokens=np.asarray(tokens, np.int32),
                         start_pos=start_pos,
                         chunk_pages=np.asarray(chunk_pages, np.int32),
                         hist_pages=hist_pages, sampling=sampling,
                         penalties=penalties, seed=seed,
                         embeds=embeds, embeds_mask=embeds_mask)
        token = int(self.prefill_batch(
            [seq], count_rows=None if count_row is None
            else count_row[None])[0])
        return token, self.last_prefill_logits[:1]

    def set_count_rows(self, slots: list[int], rows: np.ndarray) -> None:
        """Install penalty-count rows for slots whose first token is
        already known host-side (chunked-prefill finish, KV-injected
        admission): the engine builds the row including that token."""
        with self.mesh:
            self.counts_dev = self.counts_dev.at[
                jnp.asarray(np.asarray(slots, np.int32))].set(
                jnp.asarray(rows, jnp.uint8))

    def bucket_pages_for(self, needed: int) -> int:
        """Page-table width bucket (power of two, >= 8) for the decode
        window."""
        b = 8
        maxp = self.config.max_pages_per_seq
        while b < needed and b < maxp:
            b *= 2
        return min(b, maxp)

    def decode_window(self, packed: np.ndarray, window: int):
        """Dispatch one M-step decode window.

        packed [B, PK_PREFIX + bucket_pages] int32 (see PK_* columns).
        Returns (toks [M,B], lp [M,B], top_v [M,B,K], top_i [M,B,K])
        device arrays (fetch with np.asarray when needed; start async
        copies early via .copy_to_host_async()). The logprob arrays are
        zeros unless some slot set PK_LOGPROB.
        """
        bucket_pages = packed.shape[1] - PK_PREFIX
        # Specialize on whether any slot carries penalties THIS window —
        # derived from the packed array, so multihost followers replaying
        # the same control data pick the same program.
        penalized = bool(packed[:, PK_FREQPEN].any()
                         or packed[:, PK_PRESPEN].any())
        seeded = bool(packed[:, PK_SEEDED].any())
        fn = self._get_window(window, bucket_pages, penalized, seeded)
        kw = {} if self.lora is None else {"lora": self.lora}
        with self.mesh:
            if penalized:
                (toks, lps, top_vs, top_is, self.tokens_dev, self.k_cache,
                 self.v_cache, self._rng, self.counts_dev) = fn(
                    self.params, self.k_cache, self.v_cache,
                    self.tokens_dev, jnp.asarray(packed), self._rng,
                    self.counts_dev, **kw)
            else:
                (toks, lps, top_vs, top_is, self.tokens_dev, self.k_cache,
                 self.v_cache, self._rng) = fn(
                    self.params, self.k_cache, self.v_cache,
                    self.tokens_dev, jnp.asarray(packed), self._rng, **kw)
        return toks, lps, top_vs, top_is

    def embed(self, token_lists: list[list[int]],
              pooling: str = "last") -> np.ndarray:
        """Pooled, L2-normalized embeddings [n, H] for a batch of prompts
        (compiled per (bucket, batch-bucket, pooling))."""
        from dynamo_tpu.engine.model import embed_forward
        cfg = self.config
        spec = self.spec
        if not token_lists or any(not t for t in token_lists):
            raise ValueError("embeddings need at least one non-empty input")
        n_max = max(len(t) for t in token_lists)
        if n_max > cfg.prefill_buckets[-1]:
            raise ValueError(
                f"embedding input of {n_max} tokens exceeds the largest "
                f"prefill bucket ({cfg.prefill_buckets[-1]})")
        bucket = cfg.bucket_for(n_max)
        bp = 1
        while bp < len(token_lists):
            bp *= 2
        key = ("embed", bucket, bp, pooling)
        fn = self._window_cache.get(key)
        if fn is None:
            fn = perf.instrumented_jit(
                "embed", lambda p, t, sl: embed_forward(
                    p, spec, t, sl, pooling=pooling), key=key)
            self._window_cache[key] = fn
        toks = np.zeros((bp, bucket), np.int32)
        lens = np.ones((bp,), np.int32)
        for i, t in enumerate(token_lists):
            toks[i, :len(t)] = t
            lens[i] = len(t)
        with self.mesh:
            out = fn(self.params, jnp.asarray(toks), jnp.asarray(lens))
        return np.asarray(jax.device_get(out))[:len(token_lists)]

    # -- KV page transfer (disaggregation data plane) -------------------------
    def _get_extract(self, n: int):
        key = ("extract", n)
        fn = self._window_cache.get(key)
        if fn is None:
            def gather(k_cache, v_cache, pages):
                if isinstance(k_cache, QuantKV):
                    # Compressed extract: (data int8, scale f32) — packed
                    # into the uint8 wire parcel host-side.
                    return (jnp.stack([k_cache.data[:, :, pages],
                                       v_cache.data[:, :, pages]]),
                            jnp.stack([k_cache.scale[:, :, pages],
                                       v_cache.scale[:, :, pages]]))
                return jnp.stack([k_cache[:, :, pages], v_cache[:, :, pages]])
            if jax.process_count() > 1:
                # Multi-controller: the pool shards over (pp, tp) across
                # HOSTS, so replicate the gathered pages (XLA all-gathers
                # over ICI/DCN) — every host then holds the full parcel
                # and the leader's host fetch is purely local. This is the
                # cross-host gather that unblocks disagg + tiering in
                # multi-host mode (round-3 VERDICT missing #2).
                fn = perf.instrumented_jit(
                    "extract", gather, key=key,
                    out_shardings=NamedSharding(self.mesh, P()))
            else:
                fn = perf.instrumented_jit("extract", gather, key=key)
            self._window_cache[key] = fn
        return fn

    def _get_insert(self, n: int):
        key = ("insert", n)
        fn = self._window_cache.get(key)
        if fn is None:
            if self.quant_kv == "int8":
                def scatter(k_cache, v_cache, kvq, kvs, pages):
                    k_cache = QuantKV(
                        k_cache.data.at[:, :, pages].set(kvq[0]),
                        k_cache.scale.at[:, :, pages].set(kvs[0]))
                    v_cache = QuantKV(
                        v_cache.data.at[:, :, pages].set(kvq[1]),
                        v_cache.scale.at[:, :, pages].set(kvs[1]))
                    return k_cache, v_cache
            else:
                def scatter(k_cache, v_cache, kv, pages):
                    k_cache = k_cache.at[:, :, pages].set(kv[0])
                    v_cache = v_cache.at[:, :, pages].set(kv[1])
                    return k_cache, v_cache
            fn = perf.instrumented_jit("insert", scatter, key=key,
                                       donate_argnums=(0, 1))
            self._window_cache[key] = fn
        return fn

    @staticmethod
    def _page_bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    # -- perf plane (engine/perf.py; docs/OBSERVABILITY.md) -------------------
    def hbm_stats(self) -> dict:
        """``device.memory_stats()`` of this process's first addressable
        mesh device, normalized to the three gauge fields. Empty dict on
        backends without the API (CPU tests) — the perf pane degrades,
        never raises."""
        try:
            devices = list(self.mesh.devices.flat)
            local = [d for d in devices
                     if d.process_index == jax.process_index()]
            stats = (local[0] if local else devices[0]).memory_stats()
        except Exception:  # noqa: BLE001 — optional, backend-dependent API
            return {}
        if not stats:
            return {}
        return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0))}

    def memory_breakdown(self) -> dict:
        """Params / KV-pool / workspace attribution of device memory from
        this runner's own ledgers (the breakdown memory_stats can't
        give): workspace = measured in-use minus the two known pools,
        None when the backend has no memory_stats."""
        hbm = self.hbm_stats()
        in_use = hbm.get("bytes_in_use")
        return {
            "params_bytes": self.param_bytes,
            "kv_pool_bytes": self.kv_pool_bytes,
            "workspace_bytes": (max(0, in_use - self.param_bytes
                                    - self.kv_pool_bytes)
                                if in_use is not None else None),
        }

    def d2h_fetch_floor_ms(self) -> float:
        """Measured per-fetch device->host latency floor (cached probe).
        Local attachments: ~0.1 ms. Tunneled chips: ~100 ms — there,
        SPLITTING an extract into pipelined page groups is
        counterproductive (each group pays the floor; measured 0.21x on
        the dev tunnel, profile_kv_transfer.py), so extract grouping
        gates on this number."""
        if getattr(self, "_d2h_floor_ms", None) is None:
            with self.mesh:
                arr = jnp.arange(256, dtype=jnp.int32)
            np.asarray(arr)  # warm any lazy init
            best = float("inf")
            for i in range(3):
                with self.mesh:
                    a = jnp.full((256,), i, jnp.int32)
                a.block_until_ready()
                t0 = time.monotonic()
                np.asarray(a)
                best = min(best, (time.monotonic() - t0) * 1e3)
            self._d2h_floor_ms = best
        return self._d2h_floor_ms

    def extract_pages_async(self, pages: list[int]):
        """Dispatch the page gather and start the device->host copy WITHOUT
        blocking (offload path: the extract is stream-ordered before any
        later program that reuses the pages, and the host fetch overlaps
        subsequent windows). Finalize with ``finalize_extract``."""
        n = len(pages)
        nb = self._page_bucket(n)
        idx = np.zeros(nb, np.int32)
        idx[:n] = pages
        with self.mesh:
            out = self._get_extract(nb)(self.k_cache, self.v_cache,
                                        jnp.asarray(idx))
        # Multihost followers replay this dispatch for the collectives
        # only — never fetch: the result is leader-read, and N-1 wasted
        # full-parcel D2H copies would fight the offload path for host
        # bandwidth.
        if jax.process_index() == 0:
            for leaf in (out if isinstance(out, tuple) else (out,)):
                try:
                    leaf.copy_to_host_async()
                except Exception:  # noqa: BLE001
                    pass
        return out, n

    def finalize_extract(self, handle) -> np.ndarray:
        out, n = handle
        if isinstance(out, tuple):
            # Quantized pool: pack (data, scale) into the uint8 parcel
            # (engine/kv_quant.py wire format) — ~half the bf16 bytes on
            # every tier/wire path downstream.
            data = np.asarray(jax.device_get(out[0]))[:, :, :, :n]
            scale = np.asarray(jax.device_get(out[1]))[:, :, :, :n]
            if self.kv_rep > 1:
                data = data[:, :, ::self.kv_rep]
                scale = scale[:, :, ::self.kv_rep]
            return pack_parcel(data, scale)
        out = np.asarray(jax.device_get(out))[:, :, :, :n]
        if self.kv_rep > 1:
            out = out[:, :, ::self.kv_rep]
        return out

    def extract_pages(self, pages: list[int]) -> np.ndarray:
        """Gather the given pages' K/V to host: [2, L, Nkv, n, page, D]
        bf16, or with --quant-kv the PACKED int8+scales parcel
        [2, L, Nkv, n, page, D+4] uint8 at ~half the bytes (canonical
        heads either way — replicas deduplicated so parcels are portable
        across tp configurations). The disaggregation data plane's
        source side (role of the reference's NIXL reads, host-staged v0
        — SURVEY.md §5.8)."""
        return self.finalize_extract(self.extract_pages_async(pages))

    def insert_pages(self, kv: np.ndarray, pages: list[int]) -> None:
        """Write transferred K/V pages into this runner's cache. kv is a
        bf16 parcel [2, L, Nkv, n, page, D] or a PACKED int8+scales
        parcel [2, L, Nkv, n, page, D+4] uint8 (engine/kv_quant.py);
        either form converts to this runner's pool dtype on upload, so
        mixed bf16/int8 fleets interoperate. The mesh re-shards on
        upload, so TP-mismatched prefill->decode transfers work without
        a transpose kernel (the role of block_copy.cu)."""
        n = len(pages)
        assert kv.shape[3] == n, (kv.shape, n)
        if kv.shape[2] == self.canonical_nkv and self.kv_rep > 1:
            kv = np.repeat(kv, self.kv_rep, axis=2)
        assert kv.shape[2] == self.spec.num_kv_heads, (
            kv.shape, self.spec.num_kv_heads)
        nb = self._page_bucket(n)
        idx = np.zeros(nb, np.int32)
        idx[:n] = pages
        if self.quant_kv == "int8":
            if kv.dtype == np.uint8:
                data, scale = unpack_parcel(kv)
            else:
                # bf16 parcel from an unquantized peer: quantize host-side
                # (numpy twin of the in-graph kv_quantize — same rounding).
                data, scale = quantize_np(kv)
            if nb != n:
                # Pad toward the scratch page target (duplicate scatters
                # to page 0 are unordered but all-garbage).
                data = np.concatenate([data, np.zeros(
                    (*data.shape[:3], nb - n, *data.shape[4:]), np.int8)],
                    axis=3)
                scale = np.concatenate([scale, np.zeros(
                    (*scale.shape[:3], nb - n, scale.shape[4]),
                    np.float32)], axis=3)
            with self.mesh:
                self.k_cache, self.v_cache = self._get_insert(nb)(
                    self.k_cache, self.v_cache, jnp.asarray(data),
                    jnp.asarray(scale), jnp.asarray(idx))
            return
        kv = parcel_to_bf16(kv)  # packed parcels from int8 peers dequant
        if nb != n:
            # Pad with copies of the scratch page target (duplicate scatters
            # to page 0 are unordered but all-garbage).
            pad_kv = np.zeros(
                (*kv.shape[:3], nb - n, *kv.shape[4:]), kv.dtype)
            kv = np.concatenate([kv, pad_kv], axis=3)
        with self.mesh:
            self.k_cache, self.v_cache = self._get_insert(nb)(
                self.k_cache, self.v_cache, jnp.asarray(kv),
                jnp.asarray(idx))

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               page_table: np.ndarray, seq_lens: np.ndarray,
               temperature: np.ndarray, top_k: np.ndarray,
               top_p: np.ndarray) -> np.ndarray:
        """One decode step over the slot batch; returns sampled tokens [B].
        (Kept for tests/dryrun; the serving engine uses decode_window.)"""
        fn = self._get_decode()
        with self.mesh:
            sampled, self.k_cache, self.v_cache, self._rng = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(page_table), jnp.asarray(seq_lens),
                jnp.asarray(temperature), jnp.asarray(top_k),
                jnp.asarray(top_p), self._rng)
        return np.asarray(jax.device_get(sampled))


def _already_quantized(params) -> bool:
    from dynamo_tpu.engine.quant import QTensor
    return isinstance(params.get("embed"), QTensor)


def _replicate_kv_heads(params, spec, rep: int):
    """Duplicate each canonical KV head ``rep`` times in wk/wv (+ biases) so
    the effective head axis equals tp. Canonical head g lands at effective
    heads [g*rep, (g+1)*rep)."""
    d = spec.head_dim
    nkv = spec.num_kv_heads

    def rep_w(w):  # [L, h, nkv*d] -> [L, h, nkv*rep*d]
        L, h, _ = w.shape
        return np.asarray(w).reshape(L, h, nkv, d).repeat(rep, axis=2) \
            .reshape(L, h, nkv * rep * d)

    def rep_b(b):  # [L, nkv*d] -> [L, nkv*rep*d]
        L, _ = b.shape
        return np.asarray(b).reshape(L, nkv, d).repeat(rep, axis=1) \
            .reshape(L, nkv * rep * d)

    layers = dict(params["layers"])
    layers["wk"] = rep_w(layers["wk"])
    layers["wv"] = rep_w(layers["wv"])
    if "bk" in layers:
        layers["bk"] = rep_b(layers["bk"])
        layers["bv"] = rep_b(layers["bv"])
    out = dict(params)
    out["layers"] = layers
    return out


def _prefill_with_history(params, spec, k_cache, v_cache, tokens, positions,
                          page_table, seq_lens, hist_table, hist_lens,
                          attention_impl, sp_shard: bool = False,
                          x_embeds=None, embeds_mask=None,
                          lora=None, adapter_ids=None):
    """Chunked prefill: like prefill_forward but queries also attend to the
    sequence's earlier pages (read via the paged path). x_embeds/embeds_mask
    override token embeddings under multimodal media spans (rows are
    chunk-relative), so media anywhere in a long prompt — not just the
    first chunk — injects correctly."""
    import jax
    import jax.numpy as jnp
    from dynamo_tpu.engine.model import (
        _split_heads, apply_rope, embed_lookup, ffn_block, lm_logits, mm,
        rms_norm, rope_tables)

    b, s = tokens.shape
    d = spec.head_dim
    nkv = spec.num_kv_heads
    page = k_cache.shape[3]
    L = spec.num_layers
    x = embed_lookup(params["embed"], tokens)
    if x_embeds is not None:
        x = jnp.where(embeds_mask[..., None], x_embeds.astype(x.dtype), x)
    if sp_shard:
        x = jax.lax.with_sharding_constraint(x, P(None, "sp", None))
    cos, sin = rope_tables(positions, d, spec.rope_theta)
    valid = jnp.arange(s)[None, :] < seq_lens[:, None]
    maxp = hist_table.shape[1]

    def layer_fn(x, scan_in):
        if lora is not None:
            lp, layer, ll = scan_in
        else:
            (lp, layer), ll = scan_in, None
        h = rms_norm(x, lp["input_norm"], spec.rms_norm_eps)
        q = mm(h, lp["wq"], "bsh,hd->bsd")
        k = mm(h, lp["wk"], "bsh,hd->bsd")
        v = mm(h, lp["wv"], "bsh,hd->bsd")
        if ll is not None:
            from dynamo_tpu.engine.model import qkv_lora
            q, k, v = qkv_lora(q, k, v, h, ll, adapter_ids)
        if spec.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = _split_heads(q, spec.num_heads, d)
        k = _split_heads(k, nkv, d)
        v = _split_heads(v, nkv, d)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # In-chunk causal scores (grouped GQA, no repeat).
        qg = q.reshape(b, s, nkv, spec.q_per_kv, d)
        chunk_scores = jnp.einsum("bqngd,bknd->bngqk", qg, k,
                                  preferred_element_type=jnp.float32)
        causal = (positions[:, None, None, :, None]
                  >= positions[:, None, None, None, :])
        chunk_scores = jnp.where(causal & valid[:, None, None, None, :],
                                 chunk_scores, -1e30)
        # History over prior pages: layer+head-folded gather from the
        # stacked cache straight into the dot's [Nkv,B,L,D] layout
        # (hist pages are disjoint from this chunk's pages, whose
        # writes are deferred out of the scan).
        from dynamo_tpu.engine.kv_quant import gather_pages_folded
        k_hist = gather_pages_folded(k_cache, layer, hist_table)
        v_hist = gather_pages_folded(v_cache, layer, hist_table)
        hist_scores = jnp.einsum("bqngd,nbld->bngql", qg, k_hist,
                                 preferred_element_type=jnp.float32)
        hist_valid = (jnp.arange(maxp * page)[None, :]
                      < hist_lens[:, None])[:, None, None, None, :]
        hist_scores = jnp.where(hist_valid, hist_scores, -1e30)
        scores = jnp.concatenate([hist_scores, chunk_scores], axis=-1)
        scores = scores / jnp.sqrt(jnp.float32(d))
        probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
        p_hist, p_chunk = jnp.split(probs, [maxp * page], axis=-1)
        attn = (jnp.einsum("bngql,nbld->bqngd", p_hist, v_hist)
                + jnp.einsum("bngqk,bknd->bqngd", p_chunk, v))
        attn = attn.reshape(b, s, -1)
        proj = mm(attn, lp["wo"], "bsd,dh->bsh")
        if ll is not None:
            from dynamo_tpu.engine.model import lora_delta
            proj = proj + lora_delta(attn, ll["wo"], adapter_ids)
        x = x + proj
        h2 = rms_norm(x, lp["post_attn_norm"], spec.rms_norm_eps)
        x = x + ffn_block(h2, lp, spec, ll, adapter_ids)
        return x, (k, v)

    xs = ((params["layers"], jnp.arange(L), lora) if lora is not None
          else (params["layers"], jnp.arange(L)))
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    k_blocks = (k_new.reshape(L, b * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    v_blocks = (v_new.reshape(L, b * (s // page), page, nkv, d)
                .transpose(0, 3, 1, 2, 4))
    flat = page_table.reshape(-1)
    from dynamo_tpu.engine.kv_quant import scatter_pages
    k_cache = scatter_pages(k_cache, k_blocks, flat)
    v_cache = scatter_pages(v_cache, v_blocks, flat)
    x = rms_norm(x, params["final_norm"], spec.rms_norm_eps)
    last_idx = jnp.maximum(seq_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = lm_logits(x_last, params, spec)
    return logits, k_cache, v_cache
