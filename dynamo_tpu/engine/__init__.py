"""The TPU engine: a JAX/Pallas continuous-batching LLM server.

This is the component the reference does NOT provide (it orchestrates vLLM/
SGLang/TRT-LLM underneath, SURVEY.md §0); a TPU-native framework must supply
the engine itself. Design (SURVEY.md §7 stage 4):

- decoder-only transformer (Llama/Qwen2 families) in pure functional JAX,
  bfloat16, parameters sharded over a ``("dp", "tp")`` device mesh;
- paged KV cache in HBM: [layers, pages, page_size, kv_heads, head_dim],
  page tables per running sequence, host-side page allocator;
- prefill: length-bucketed dense causal attention (one compiled program per
  bucket); decode: single-token step over a fixed slot batch with paged
  attention (custom Pallas kernel on TPU, gather-based XLA fallback on CPU);
- continuous batching scheduler admitting prefills between decode steps,
  emitting KV events + ForwardPassMetrics for the router.
"""

from dynamo_tpu.engine.config import ModelSpec, EngineConfig
from dynamo_tpu.engine.engine import TPUEngine

__all__ = ["EngineConfig", "ModelSpec", "TPUEngine"]
