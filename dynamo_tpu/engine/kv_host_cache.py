"""Multi-tier KV block cache: G2 host-DRAM + G3 disk.

TPU-native counterpart of the reference KVBM's offload hierarchy
(lib/llm/src/block_manager.rs:72-82 G1..G4; block_manager/offload.rs): G1
is the in-HBM PageAllocator (kv_cache.py); pages evicted from G1 under
pressure are OFFLOADED here instead of dropped — the engine extracts them
to host asynchronously (overlapping the next windows' compute) and a
prefix-cache hit on a spilled block ONBOARDS it with a device upload
instead of recomputing the prefill.

Blocks are keyed by the chained block hash (llm/tokens.py), so a block's
content is immutable for its key: tiers never need invalidation, only
capacity eviction (LRU). Entries are canonical-nkv host arrays
[2, L, Nkv, page, D] bf16 — or, with ``--quant-kv int8``, the packed
int8+scales parcel [2, L, Nkv, page, D+4] uint8 (engine/kv_quant.py) at
~half the bytes, i.e. ~2x blocks per GB of tier budget. Both forms are
portable across tp configurations like the disaggregation parcels.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kv_host_cache")

# Tiers are mutated by the engine thread AND read by KV-plane connection
# threads serving peer G4 block fetches (llm/kv_plane.py block_provider):
# one lock covers both tiers' OrderedDict surgery (entries are immutable
# once stored — content-hashed — so only the index needs protecting).



class DiskKVCache:
    """G3: block files under a directory, LRU-evicted by capacity
    (reference G3 disk pool, block_manager/offload.rs)."""

    def __init__(self, directory: str, capacity_pages: int = 4096):
        self.dir = directory
        self.capacity = capacity_pages
        os.makedirs(directory, exist_ok=True)
        # hash -> path, insertion-ordered for LRU.
        self._index: OrderedDict[int, str] = OrderedDict()
        self._lock = threading.Lock()
        for name in sorted(os.listdir(directory)):
            if name.endswith(".npy"):
                try:
                    self._index[int(name[:-4], 16)] = os.path.join(directory,
                                                                   name)
                except ValueError:
                    continue
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.block_nbytes = 0  # last stored block's size (uniform per model)

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            return block_hash in self._index

    def put(self, block_hash: int, kv: np.ndarray) -> None:
        with self._lock:
            if block_hash in self._index:
                self._index.move_to_end(block_hash)
                return
        path = os.path.join(self.dir, f"{block_hash & (2**64 - 1):016x}.npy")
        self.puts += 1
        self.block_nbytes = kv.nbytes
        # View bf16 as uint16 for npy portability; packed int8+scales
        # parcels (uint8, --quant-kv — engine/kv_quant.py) save natively
        # at ~half the bytes.
        np.save(path, kv if kv.dtype == np.uint8 else kv.view(np.uint16))
        evicted: list[str] = []
        with self._lock:
            self._index[block_hash] = path
            while len(self._index) > self.capacity:
                _, old = self._index.popitem(last=False)
                evicted.append(old)
        for old in evicted:
            try:
                os.remove(old)
            except OSError:
                pass

    def get(self, block_hash: int) -> np.ndarray | None:
        import ml_dtypes
        with self._lock:
            path = self._index.get(block_hash)
        if path is None:
            self.misses += 1
            return None
        try:
            arr = np.load(path)
            if arr.dtype == np.uint16:  # bf16 stored as uint16
                arr = arr.view(ml_dtypes.bfloat16)
        except (OSError, ValueError):
            with self._lock:
                self._index.pop(block_hash, None)
            self.misses += 1
            return None
        with self._lock:
            if block_hash in self._index:
                self._index.move_to_end(block_hash)
        self.hits += 1
        return arr


class HostKVCache:
    """G2: bounded host-DRAM block pool. Capacity overflow cascades to the
    G3 disk tier when configured (reference offload_to_disk path)."""

    def __init__(self, capacity_pages: int,
                 disk: DiskKVCache | None = None):
        self.capacity = capacity_pages
        self.disk = disk
        self._blocks: OrderedDict[int, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.spills_in = 0       # blocks offloaded into this tier
        self.demotions = 0       # G2 -> G3 capacity evictions
        self.block_nbytes = 0    # last stored block's size (uniform)

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    def put(self, block_hash: int, kv: np.ndarray,
            promotion: bool = False) -> None:
        demoted: list[tuple[int, np.ndarray]] = []
        with self._lock:
            if block_hash in self._blocks:
                self._blocks.move_to_end(block_hash)
                return
            # Own the memory: callers hand views into large batched extract
            # buffers — storing the view would pin the whole base array and
            # blow the capacity bound by the padding/replication factor.
            self._blocks[block_hash] = np.ascontiguousarray(kv)
            self.puts += 1
            self.block_nbytes = int(kv.nbytes)
            if not promotion:
                self.spills_in += 1
            while len(self._blocks) > self.capacity:
                demoted.append(self._blocks.popitem(last=False))
        if self.disk is not None:
            for old_hash, old_kv in demoted:
                self.disk.put(old_hash, old_kv)
                self.demotions += 1

    def get(self, block_hash: int) -> np.ndarray | None:
        with self._lock:
            kv = self._blocks.get(block_hash)
            if kv is not None:
                self._blocks.move_to_end(block_hash)
        if kv is not None:
            self.hits += 1
            return kv
        if self.disk is not None:
            kv = self.disk.get(block_hash)
            if kv is not None:
                # Promote back into DRAM (not an offload: stats stay true).
                self.put(block_hash, kv, promotion=True)
                self.hits += 1
                return kv
        self.misses += 1
        return None

    def clear(self) -> None:
        """Drop every tier (admin clear_kv_blocks): G2 memory and the G3
        disk files behind it."""
        with self._lock:
            self._blocks.clear()
        if self.disk is not None:
            with self.disk._lock:
                index, self.disk._index = dict(self.disk._index), \
                    OrderedDict()
            for h, path in index.items():
                try:
                    os.remove(path)
                except OSError:
                    pass

    def stats(self) -> dict:
        n_g2 = len(self._blocks)
        out = {"g2_blocks": n_g2, "g2_hits": self.hits,
               "g2_misses": self.misses, "g2_puts": self.puts,
               "g2_spills_in": self.spills_in,
               "g2_demotions": self.demotions,
               "g2_capacity": self.capacity,
               "g2_bytes": n_g2 * self.block_nbytes}
        if self.disk is not None:
            n_g3 = len(self.disk._index)
            out.update({"g3_blocks": n_g3,
                        "g3_hits": self.disk.hits,
                        "g3_misses": self.disk.misses,
                        "g3_puts": self.disk.puts,
                        "g3_capacity": self.disk.capacity,
                        "g3_bytes": n_g3 * self.disk.block_nbytes})
        return out

    def block_hashes(self, limit: int = 0) -> list[int]:
        """Snapshot of resident G2 block hashes (inventory digests);
        ``limit`` > 0 caps the copy."""
        with self._lock:
            keys = list(self._blocks.keys())
        return keys[:limit] if limit else keys
